"""Keep tests/mutation_audit.py from rotting.

The audit's value rests on each mutation's `old` pattern matching the
live source: a refactor that renames a constant or reflows a line would
otherwise silently turn that mutation into a no-op and the audit into a
false "all killed". These checks run in the regular suite (milliseconds,
no subprocesses) so pattern drift turns the suite red in the same
commit that caused it.

Deliberately NOT copied into the audit's mutated runs (mutation_audit
passes --ignore for this file): under any source mutation the pattern
assertion below fails by construction, which would count as a free
"kill" for every mutant and void the audit. See the audit's module
docstring.
"""

import json
import shutil
import subprocess

import mutation_audit


def test_every_mutation_pattern_matches_live_source_exactly_once():
    for name, relpath, old, new, _property in mutation_audit.MUTATIONS:
        source = (mutation_audit.REPO / relpath).read_text()
        occurrences = source.count(old)
        assert occurrences == 1, (
            f"mutation {name!r}: pattern occurs {occurrences}x in {relpath} "
            "(must be exactly 1 — update tests/mutation_audit.py in the "
            "same commit as the source refactor)"
        )
        assert old != new, f"mutation {name!r} is a no-op"


def test_docs_cite_the_live_mutant_count():
    """The mutant count appears in PRESENT-TENSE prose (README, the
    verify skill) that must track the live MUTATIONS tuple forever —
    and it has drifted under growth three times already (one advisor
    finding, two review findings). Enforce the sync mechanically:
    growing the audit without updating the docs turns the suite red in
    the same commit. Per-round history lines ("suite N passed" in old
    round records) are deliberately NOT enforced — history is frozen;
    only present-tense claims must track the code."""
    n = len(mutation_audit.MUTATIONS)
    readme = (mutation_audit.REPO / "README.md").read_text()
    assert f"{n} targeted mutants" in readme
    assert f"{n}/{n} killed" in readme
    skill = (
        mutation_audit.REPO / ".claude" / "skills" / "verify" / "SKILL.md"
    ).read_text()
    assert f"current {n} mutants" in skill


def test_mutations_cover_every_policed_surface():
    """bench + gate (the honesty machinery), jaxlint (the lint rules
    whose corpus test is itself a policed property since PR 2), the
    incremental ingest layer (equivalence/threshold/peak-bucket, PR 3),
    since PR 4 the overlapped pipeline (packer liveness) plus the
    arena bench's async equivalence gate, since PR 5 the serving
    layer (silent-partial-restore, staleness policy, snapshot version
    gate), since PR 6 the observability layer (histogram bucket
    semantics, stats() sentinel absorption, the soak hard gate), since
    PR 7 the diagnosis layer (exemplar bucket placement, the flight
    recorder's registry dump, the watchdog's tolerance direction), and
    since PR 9 the network tier (sequence order at the merge, the
    shed-coalesce summary update, the wire response envelope), and since
    PR 10 the jaxlint v2 engine (the symbol table's import resolution,
    the held-lock scanner's with-block tracking, the lock-order graph's
    edges, the JSON output schema), and since PR 11 the jaxlint v3
    abstract interpreter (the shape-lattice join, the recognized
    bucketing-op set, the taint sanitizer check), and since PR 13 the
    live ops plane (the sliding window's ring rotation, the SLO
    burn-rate threshold direction, the /debug wire envelope), and since
    PR 14 the jaxlint v4 lifecycle analyzer (the CFG's exception edge,
    the terminal-state transition, the one-hop helper-release
    credit), and since PR 15 the jaxlint v5 effect-contract analyzer
    (the call-graph fixpoint, the check-then-act re-check credit, the
    pure-render parameter exemption), and since PR 16 the fast wire
    path (the byte cache's view-generation check, the batch endpoint's
    one-view contract, the event-loop read front end's default), and
    since PR 17 the jaxlint v6 schema analyzer (the shape-fact
    extractor, the version-bump comparison direction, the replication
    closure's fixpoint), and since PR 18 the replication layer (the
    replica's strict-sequence apply, the incremental snapshot chain's
    base-identity link, the staleness objective's burn-rate pull), and
    since PR 19 the multi-tenant plane (the composite-id tenant key,
    the pow2 tenant bucket, the wire tenant sanitizer), and since
    PR 20 the matchmaking plane (the active policy's CI-width blend,
    the matchloop convergence gate, the /match envelope watermark)."""
    files = {relpath for _n, relpath, _o, _nw, _p in mutation_audit.MUTATIONS}
    assert files == {
        "bench.py",
        "verify_reference.py",
        "arena/engine.py",
        "arena/tenancy.py",
        "arena/analysis/jaxlint.py",
        "arena/analysis/project.py",
        "arena/analysis/absint.py",
        "arena/analysis/cfg.py",
        "arena/analysis/lifecycle.py",
        "arena/analysis/effects.py",
        "arena/analysis/schema.py",
        "arena/ingest.py",
        "arena/pipeline.py",
        "arena/serving.py",
        "arena/bench_arena.py",
        "arena/obs/metrics.py",
        "arena/obs/debug.py",
        "arena/obs/regress.py",
        "arena/obs/windows.py",
        "arena/obs/slo.py",
        "arena/net/frontdoor.py",
        "arena/net/protocol.py",
        "arena/net/server.py",
        "arena/net/fastpath.py",
        "arena/net/replica.py",
        "arena/match/matchmaker.py",
    }


def test_copied_set_exists_and_excludes_git():
    for name in mutation_audit.COPIED:
        assert (mutation_audit.REPO / name).exists(), name
    assert ".git" not in mutation_audit.COPIED


# --- Verdict plumbing, in-process (run_suite/make_copy faked so no ---
# --- pytest subprocesses run; the real end-to-end audit is on-demand) ---


def _FakeProc(returncode, stdout=""):
    """Type-faithful stand-in for run_suite's return value."""
    return subprocess.CompletedProcess(args=[], returncode=returncode, stdout=stdout)


def _fake_sources_only(dest):
    """Stand-in for make_copy: just the mutable sources, so the
    mutation patterns resolve without dragging the whole tree along."""
    for name in (
        "bench.py",
        "verify_reference.py",
        "arena/engine.py",
        "arena/tenancy.py",
        "arena/analysis/jaxlint.py",
        "arena/analysis/project.py",
        "arena/analysis/absint.py",
        "arena/analysis/cfg.py",
        "arena/analysis/lifecycle.py",
        "arena/analysis/effects.py",
        "arena/analysis/schema.py",
        "arena/ingest.py",
        "arena/pipeline.py",
        "arena/serving.py",
        "arena/bench_arena.py",
        "arena/obs/metrics.py",
        "arena/obs/debug.py",
        "arena/obs/regress.py",
        "arena/obs/windows.py",
        "arena/obs/slo.py",
        "arena/net/frontdoor.py",
        "arena/net/protocol.py",
        "arena/net/server.py",
        "arena/net/fastpath.py",
        "arena/net/replica.py",
        "arena/match/matchmaker.py",
    ):
        target = dest / name
        target.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(mutation_audit.REPO / name, target)


def _audit_json(capsys):
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_all_mutants_killed_exits_0(monkeypatch, capsys):
    calls = []

    def fake_run_suite(copy):
        calls.append(copy)
        # First call is the clean-copy sanity check; every mutated run red.
        return _FakeProc(0 if len(calls) == 1 else 1)

    monkeypatch.setattr(mutation_audit, "make_copy", _fake_sources_only)
    monkeypatch.setattr(mutation_audit, "run_suite", fake_run_suite)
    assert mutation_audit.main() == 0
    summary = _audit_json(capsys)
    assert summary["killed"] == summary["total"] == len(mutation_audit.MUTATIONS)
    assert summary["survived"] == []
    assert len(calls) == 1 + len(mutation_audit.MUTATIONS)


def test_surviving_mutant_exits_1_and_is_named(monkeypatch, capsys):
    survivor = mutation_audit.MUTATIONS[2][0]
    calls = []

    def fake_run_suite(copy):
        calls.append(copy)
        # Clean check green; mutant #3's run also green = SURVIVED.
        return _FakeProc(0 if len(calls) in (1, 4) else 1)

    monkeypatch.setattr(mutation_audit, "make_copy", _fake_sources_only)
    monkeypatch.setattr(mutation_audit, "run_suite", fake_run_suite)
    assert mutation_audit.main() == 1
    summary = _audit_json(capsys)
    assert [s["name"] for s in summary["survived"]] == [survivor]
    assert summary["survived"][0]["property"]  # names the broken property


def test_mutation_restores_source_even_when_suite_run_crashes(
    monkeypatch, capsys
):
    """A crash mid-run must not leave the temp copy mutated (the finally
    restore) and must exit the distinct crash code 3 with a JSON error
    line — never rc 1, which means 'a mutant survived'."""
    calls = []
    seen_texts = []
    kept_dirs = []

    def fake_run_suite(copy):
        calls.append(copy)
        if len(calls) == 1:
            return _FakeProc(0)
        seen_texts.append((copy / mutation_audit.MUTATIONS[0][1]).read_text())
        raise RuntimeError("pytest runner died")

    real_rmtree = shutil.rmtree  # the patch below is module-global

    def keep_dir(path, ignore_errors=False):
        kept_dirs.append(path)  # skip cleanup so the restore is observable

    monkeypatch.setattr(mutation_audit, "make_copy", _fake_sources_only)
    monkeypatch.setattr(mutation_audit, "run_suite", fake_run_suite)
    monkeypatch.setattr(mutation_audit.shutil, "rmtree", keep_dir)
    try:
        assert mutation_audit.main() == 3
        summary = _audit_json(capsys)
        assert summary["error"] == "audit_crashed"
        assert "RuntimeError" in summary["detail"]
        name, relpath, old, new, _prop = mutation_audit.MUTATIONS[0]
        # The mutated text was in place when the run crashed (the audit
        # was really measuring the mutant, not the pristine source)...
        assert new in seen_texts[0] and old not in seen_texts[0]
        # ...and the finally-restore put the pristine source back even
        # though the run raised.
        restored = (calls[1] / relpath).read_text()
        assert restored == (mutation_audit.REPO / relpath).read_text()
    finally:
        for path in kept_dirs:
            real_rmtree(path, ignore_errors=True)


def test_red_clean_copy_exits_2_without_applying_mutants(monkeypatch, capsys):
    runs = []

    def fake_run_suite(copy):
        runs.append(copy)
        return _FakeProc(1, stdout="1 failed")

    monkeypatch.setattr(mutation_audit, "make_copy", _fake_sources_only)
    monkeypatch.setattr(mutation_audit, "run_suite", fake_run_suite)
    assert mutation_audit.main() == 2
    assert _audit_json(capsys)["error"] == "clean_copy_suite_red"
    assert len(runs) == 1  # no mutated runs after an unmeasurable baseline


def test_stale_pattern_counts_as_survived(monkeypatch, capsys):
    stale = ("stale-mutant", "bench.py", "THIS PATTERN DOES NOT EXIST", "x", "p")
    monkeypatch.setattr(
        mutation_audit, "MUTATIONS", (stale,) + mutation_audit.MUTATIONS[1:]
    )
    calls = []

    def fake_run_suite(copy):
        calls.append(copy)
        return _FakeProc(0 if len(calls) == 1 else 1)

    monkeypatch.setattr(mutation_audit, "make_copy", _fake_sources_only)
    monkeypatch.setattr(mutation_audit, "run_suite", fake_run_suite)
    assert mutation_audit.main() == 1
    summary = _audit_json(capsys)
    assert summary["survived"] == [
        {"name": "stale-mutant", "reason": "pattern_missing", "property": "p"}
    ]
