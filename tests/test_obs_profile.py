"""Sampling-profiler contracts (arena/obs/profile.py).

The load-bearing properties:

- role attribution: samples fold under the system's stable thread-role
  names (packer/dispatcher/http-*/...), keyed by the thread-name
  constants the worker modules export — so "where does the packer's
  wall clock go" survives thread restarts;
- the collapsed-stack read is flamegraph-shaped (root-first
  `role;f1;f2 count` lines, hottest first) and lands in the debug
  bundle as `profile.txt`;
- the stack table is bounded: overflow increments `truncated`, never
  grows memory;
- PR 10 liveness (ISSUE 13 satellite f): a dead sampler thread is an
  explicit `ProfilerError` on every blocked wait and a non-None
  health error that surfaces through `ArenaServer.stats()` — never a
  silently frozen profile;
  test_dead_sampler_surfaces_error_in_stats_never_a_silent_hang is
  the pin.
"""

import json
import threading
import time

import pytest

from arena import obs as obs_pkg
from arena.net.frontdoor import MERGE_THREAD_NAME
from arena.obs import debug
from arena.obs import profile as profile_mod
from arena.obs.profile import (
    NullProfiler,
    ProfilerError,
    SamplingProfiler,
    thread_role,
)
from arena.pipeline import PACKER_THREAD_NAME
from arena.serving import ArenaServer


def test_thread_roles_match_the_system_thread_names():
    """The role table keys off the SAME name constants the worker
    modules spawn under — renaming a thread without updating the
    profiler's table breaks attribution, and this pins it."""
    assert thread_role(PACKER_THREAD_NAME) == "packer"
    assert thread_role(MERGE_THREAD_NAME) == "dispatcher"
    assert thread_role("arena-wire-server") == "http-accept"
    assert thread_role("Thread-3 (process_request_thread)") == "http-worker"
    assert thread_role("arena-obs-window") == "window"
    assert thread_role("arena-obs-profiler") == "profiler"
    assert thread_role("MainThread") == "other"


def test_sample_now_attributes_named_threads_to_roles():
    prof = SamplingProfiler()
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            time.sleep(0.005)

    t = threading.Thread(target=spin, name=PACKER_THREAD_NAME, daemon=True)
    t.start()
    try:
        assert prof.sample_now() == 1
        snap = prof.snapshot()
        assert snap["samples"] == 1
        assert "packer" in snap["roles"]
        # The sampling thread itself (here: MainThread calling
        # sample_now) is excluded — its own act of sampling is not
        # signal — so "other" only appears for threads besides it.
        packer_rows = [r for r in snap["top"] if r["role"] == "packer"]
        assert packer_rows
        # Root-first folded frames: file:function keys, no line numbers.
        # Scan ALL packer rows, not just the hottest: a packer-named
        # daemon thread leaked by an earlier test in the suite shares
        # the role and can tie it on counts within a single sweep.
        assert any(
            "test_obs_profile.py:spin" in r["stack"] for r in packer_rows
        )
        assert json.dumps(snap)  # the /debug/profile payload is JSON-able
    finally:
        stop.set()
        t.join()


def test_threaded_sampler_accumulates_and_survives_restart():
    prof = SamplingProfiler(hz=200.0)
    prof.start()
    try:
        assert prof.wait_for_sample(samples=3, timeout=10.0) >= 3
        assert prof.health()["running"] is True
        assert prof.health()["error"] is None
    finally:
        prof.close()
    samples_after_close = prof.samples
    assert samples_after_close >= 3
    assert prof.health()["running"] is False
    assert prof.health()["error"] is None  # a clean close is not a death
    collapsed = prof.collapsed()
    assert collapsed.endswith("\n")
    assert any(
        line.rsplit(" ", 1)[1].isdigit()
        for line in collapsed.splitlines()
    )
    # start() is a restart, not a one-shot.
    prof.start()
    try:
        assert prof.wait_for_sample(samples=1, timeout=10.0) > (
            samples_after_close
        )
    finally:
        prof.close()


def test_stack_table_is_bounded_and_counts_truncation():
    prof = SamplingProfiler(max_stacks=1)
    stop = threading.Event()

    def spin_a():
        while not stop.is_set():
            time.sleep(0.005)

    def spin_b():
        while not stop.is_set():
            time.sleep(0.005)

    ts = [
        threading.Thread(target=spin_a, name=PACKER_THREAD_NAME, daemon=True),
        threading.Thread(target=spin_b, name="arena-test-bg", daemon=True),
    ]
    for t in ts:
        t.start()
    try:
        prof.sample_now()
        health = prof.health()
        # Two distinct (role, stack) keys competed for one slot: the
        # table kept one and COUNTED the other, never grew.
        assert health["distinct_stacks"] == 1
        assert health["truncated"] >= 1
    finally:
        stop.set()
        for t in ts:
            t.join()


def test_profiler_rejects_malformed_shape():
    with pytest.raises(ProfilerError):
        SamplingProfiler(hz=0)
    with pytest.raises(ProfilerError):
        SamplingProfiler(max_stacks=0)


def test_null_profiler_is_a_true_noop_twin():
    null = NullProfiler()
    assert null.start() is null
    assert null.sample_now() == 0
    assert null.wait_for_sample() == 0
    assert null.collapsed() == ""
    assert null.snapshot()["top"] == []
    assert null.health()["error"] is None
    null.close()


# --- PR 10 liveness discipline (satellite f) -------------------------------


def test_dead_sampler_surfaces_error_in_stats_never_a_silent_hang(
    monkeypatch,
):
    """A sampler thread killed mid-run (sys._current_frames blowing
    up stands in for any interpreter-level surprise) must surface as
    (1) an explicit ProfilerError from every blocked wait, (2) a
    non-None health error, and (3) an unhealthy `slo` block in
    `ArenaServer.stats()` — the ops plane may never present a frozen
    profile as a quiet one."""

    def boom():
        raise RuntimeError("frames unavailable")

    monkeypatch.setattr(profile_mod.sys, "_current_frames", boom)
    obs = obs_pkg.Observability()
    srv = ArenaServer(num_players=8, obs=obs)
    try:
        obs.start_ops()
        with pytest.raises(ProfilerError, match="sampler thread died"):
            obs.profiler.wait_for_sample(samples=1, timeout=10.0)
        health = obs.profiler.health()
        assert health["error"] is not None
        assert "frames unavailable" in health["error"]
        block = srv.stats()["slo"]
        assert block["healthy"] is False
        assert any("frames unavailable" in e for e in block["errors"])
        assert block["profiler_health"]["error"] is not None
    finally:
        obs.stop_ops()
        srv.close()


def test_debug_bundle_carries_the_collapsed_profile(tmp_path):
    obs = obs_pkg.Observability()
    obs.enable_ops()
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            time.sleep(0.005)

    t = threading.Thread(target=spin, name=PACKER_THREAD_NAME, daemon=True)
    t.start()
    try:
        obs.profiler.sample_now()
    finally:
        stop.set()
        t.join()
    bundle = debug.dump_debug_bundle(obs, tmp_path / "bundle")
    profile_txt = (tmp_path / "bundle" / "profile.txt").read_text()
    assert profile_txt == obs.profiler.collapsed()
    assert "packer;" in profile_txt
    manifest = json.loads(
        (tmp_path / "bundle" / "MANIFEST.json").read_text()
    )
    assert "profile.txt" in manifest["files"]
    assert manifest["profiler_samples"] == 1
    assert bundle == tmp_path / "bundle"
