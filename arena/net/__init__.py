"""arena.net — the network serving tier (ROADMAP item 1).

Three parts, layered over the existing serving and pipeline stack:

- `arena.net.protocol`  — the wire protocol: route parsing, the
  response envelope (staleness watermark + request trace id in every
  JSON response), submit-body validation, and `WireClient`, the
  stdlib persistent-connection consumer half.
- `arena.net.frontdoor` — the multi-producer front door: global
  sequence numbers assigned at admission, a reorder-buffer merge that
  applies strictly in sequence order (async==sync bit-exact under N
  writers), and bounded-degradation load shedding (oldest batches
  coalesce into a summary update; the summary's backlog is staleness-
  bounded, trimming beyond it is counted, never silent).
- `arena.net.server`    — the HTTP/JSON server (stdlib only):
  /leaderboard, /player/{id}, /h2h, /query, /submit, /stats
  (Prometheus render()), /healthz, /debug/*.
- `arena.net.fastpath`  — the fast read path (PR 16): the
  watermark-keyed response byte cache, head-splice rendering (cached
  bytes completed with each request's own trace id), and the
  `selectors` event-loop front end that answers reads inline while
  /submit keeps its blocking worker pool.

What this tier deliberately defers (ROADMAP item 2): replica catch-up
— a read-only `ArenaHTTPServer(frontdoor=None)` already serves 503 on
/submit, but keeping it fresh needs incremental snapshots + log
shipping, not a wire-layer feature.
"""

from arena.net.frontdoor import (
    DEFAULT_CAPACITY,
    DEFAULT_MAX_STALENESS_MATCHES,
    POLICY_COALESCE,
    POLICY_STALENESS,
    SUMMARY_PRODUCER,
    FrontDoor,
    FrontDoorError,
)
from arena.net.fastpath import (
    DEFAULT_CACHE_CAPACITY,
    DEFAULT_PRERENDER_PAGES,
    EventLoopFrontEnd,
    ResponseCache,
)
from arena.net.protocol import (
    ENDPOINTS,
    MAX_BATCH_QUERIES,
    ProtocolError,
    WireClient,
    make_response,
    parse_path,
    parse_query_body,
    parse_submit_body,
)
from arena.net.server import ArenaHTTPServer

__all__ = [
    "ArenaHTTPServer",
    "DEFAULT_CACHE_CAPACITY",
    "DEFAULT_CAPACITY",
    "DEFAULT_MAX_STALENESS_MATCHES",
    "DEFAULT_PRERENDER_PAGES",
    "ENDPOINTS",
    "EventLoopFrontEnd",
    "FrontDoor",
    "FrontDoorError",
    "MAX_BATCH_QUERIES",
    "POLICY_COALESCE",
    "POLICY_STALENESS",
    "ProtocolError",
    "ResponseCache",
    "SUMMARY_PRODUCER",
    "WireClient",
    "make_response",
    "parse_path",
    "parse_query_body",
    "parse_submit_body",
]
