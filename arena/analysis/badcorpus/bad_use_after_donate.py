"""jaxlint corpus: reading a buffer after donating it.

`state` is donated to the update (donate_argnums=(0,)); XLA may have
reused its memory for the result, so the later read aliases freed or
overwritten storage. Rule: use-after-donate."""

import jax


def _update(state, delta):
    return state + delta


donating_update = jax.jit(_update, donate_argnums=(0,))


def step_and_leak(state, delta):
    new_state = donating_update(state, delta)
    stale = state + 1.0
    return new_state, stale
