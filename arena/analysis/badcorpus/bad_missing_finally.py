"""jaxlint corpus: the release exists — but only on the happy path.

`serve_one` pairs its `stage()` with a `release()`, so the author knew
the protocol; the pairing only holds on fall-through. The wire call
between the two can raise, and on that path the slot stays in flight
forever — the release belongs in a finally (or the whole pair behind a
context manager). Rule: missing-finally-for-paired-call."""


class StagedBuffer:  # protocol: stage->release
    def __init__(self):
        self._in_flight = 0

    def stage(self, batch):
        self._in_flight += 1
        return batch

    def release(self):
        self._in_flight -= 1


def serve_one(batch, wire):
    buf = StagedBuffer()
    buf.stage(batch)
    wire.send(batch)  # a raise here skips the release below
    buf.release()
