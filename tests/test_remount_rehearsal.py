"""End-to-end rehearsal of the remount playbook (SURVEY_REWRITE.md).

The playbook is the procedure a fresh session executes on the repo's
highest-stakes day — the day the reference mount stops being empty.
Until round 5 it had only ever been *written*, never *executed*; its
first real execution should not also be its first test. These tests
walk steps 0-3 mechanically, over both predicted remount shapes:

- a plain working tree (README/src/... — the shape the playbook's
  normal read order serves), and
- the bare-git shape BASELINE.json actually predicts ("only a bare
  .git directory"), including the materialization command the playbook
  §0b prescribes, run against a READ-ONLY mount exactly like the real
  one (mode dr-xr-xr-x).

Each numbered assertion block cites the playbook step it rehearses.
The tests use a real temp git repo for the fake repo dir so the
hygiene field (commit-the-manifest-first, step 0.4) is exercised for
real, and a real `git clone` for materialization so the committed
command is proven to work from a read-only source.
"""

import hashlib
import json
import os
import pathlib
import subprocess

from conftest import make_fake_repo

import verify_reference


def run_gate(monkeypatch, capsys, reference, repo):
    """In-process ``python verify_reference.py`` (same as the suite's
    other in-process runs; the true-subprocess contract is covered by
    the e2e fixture tests)."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(reference))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(repo))
    monkeypatch.setenv(
        "GIT_CEILING_DIRECTORIES", str(pathlib.Path(repo).parent)
    )
    rc = verify_reference.main()
    out = capsys.readouterr().out.splitlines()
    assert len(out) == 1  # the one-line stdout contract holds throughout
    return rc, json.loads(out[0])


def git_raw(cwd, *args):
    # LC_ALL=C: the commit-less rehearsal asserts on git's message
    # text, which localizes under non-English locales with gettext
    # catalogs installed.
    env = dict(os.environ, LC_ALL="C")
    return subprocess.run(
        [
            "git",
            "-C",
            str(cwd),
            "-c",
            "user.email=rehearsal@example.com",
            "-c",
            "user.name=rehearsal",
            *args,
        ],
        capture_output=True,
        text=True,
        env=env,
    )


def git(cwd, *args):
    proc = git_raw(cwd, *args)
    assert proc.returncode == 0, (args, proc.stderr)
    return proc.stdout


def repin_fingerprint(repo, count, why):
    """Playbook step 3: deliberate fingerprint re-pin, count + comment."""
    path = repo / "reference_fingerprint.json"
    fingerprint = json.loads(path.read_text())
    fingerprint["reference_entry_count"] = count
    fingerprint["comment"] = why
    path.write_text(json.dumps(fingerprint))


def chmod_read_only(root):
    """Approximate the real mount's dr-xr-xr-x: dirs 0o555, files 0o444."""
    for dirpath, dirnames, filenames in os.walk(root, topdown=False):
        for name in filenames:
            os.chmod(pathlib.Path(dirpath) / name, 0o444)
        os.chmod(dirpath, 0o555)


def chmod_writable_again(root):
    for dirpath, dirnames, filenames in os.walk(root, topdown=False):
        for name in filenames:
            os.chmod(pathlib.Path(dirpath) / name, 0o644)
        os.chmod(dirpath, 0o755)


def test_rehearsal_plain_working_tree(tmp_path, monkeypatch, capsys):
    # A plain working-tree remount: top-level build file, source, docs.
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "train.py").write_text("def train():\n    return 1\n")
    (ref / "README.md").write_text("# the real reference\n")
    (ref / "setup.py").write_text("from setuptools import setup\nsetup()\n")
    repo = make_fake_repo(tmp_path)
    git(repo, "init", "-q")
    git(repo, "add", "-A")
    git(repo, "commit", "-q", "-m", "round baseline")

    # Step 0.1: the gate observes the event — rc 1, integer count > 0.
    rc, result = run_gate(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_DRIFT
    count = result["observed"]["reference_entry_count"]
    assert isinstance(count, int) and count == 4

    # Step 0.2: independent confirmation — the gate and a direct walk
    # of the live tree must agree.
    independent = sum(len(d) + len(f) for _, d, f in os.walk(ref))
    assert independent == count

    # Step 0.3: manifest spot-check — hash a couple of regular files
    # straight off the live tree and compare; no error entries.
    manifest = json.loads(pathlib.Path(result["manifest"]).read_text())
    assert manifest["entry_count"] == count
    assert manifest["shape"] == "working-tree"
    by_path = {e["path"]: e for e in manifest["entries"]}
    assert not [e for e in manifest["entries"] if e["type"] == "error"]
    for rel in ("README.md", "src/train.py"):
        live = hashlib.sha256((ref / rel).read_bytes()).hexdigest()
        assert by_path[rel]["sha256"] == live, rel

    # Step 0.4: the hygiene field demands the manifest be committed
    # before anything else; committing it satisfies the check.
    assert result["uncommitted_round_artifacts"] == [
        verify_reference.MANIFEST_NAME
    ]
    git(repo, "add", verify_reference.MANIFEST_NAME)
    git(repo, "commit", "-q", "-m", "record observed manifest (step 0.4)")

    # Step 3: deliberate re-pin; the gate must return to rc 0 with the
    # non-empty note — NOT the emptiness claim.
    repin_fingerprint(repo, count, "rehearsal: plain-tree remount observed")
    rc, result = run_gate(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_MATCH
    assert "NON-EMPTY" in result["note"]
    assert "non-graftable verdict no longer applies" in result["note"]
    assert "still empty" not in result["note"]
    assert result["uncommitted_round_artifacts"] == []


def test_rehearsal_bare_git_shape(tmp_path, monkeypatch, capsys):
    # Build a real upstream history, then package it the way
    # BASELINE.json predicts: a mount containing ONLY .git.
    upstream = tmp_path / "upstream"
    (upstream / "src").mkdir(parents=True)
    (upstream / "src" / "model.py").write_text("LAYERS = 12\n")
    (upstream / "README.md").write_text("# hidden in the object store\n")
    git(upstream, "init", "-q")
    git(upstream, "add", "-A")
    git(upstream, "commit", "-q", "-m", "the real source")
    head = git(upstream, "rev-parse", "HEAD").strip()

    ref = tmp_path / "ref"
    ref.mkdir()
    (upstream / ".git").rename(ref / ".git")
    chmod_read_only(ref)  # the real mount is dr-xr-xr-x
    try:
        repo = make_fake_repo(tmp_path)
        git(repo, "init", "-q")
        git(repo, "add", "-A")
        git(repo, "commit", "-q", "-m", "round baseline")

        # Step 0 + §0b detection: rc 1, and the gate says VCS-only —
        # the working-file read order must NOT be trusted here.
        rc, result = run_gate(monkeypatch, capsys, ref, repo)
        assert rc == verify_reference.EXIT_DRIFT
        count = result["observed"]["reference_entry_count"]
        assert isinstance(count, int) and count > 0
        assert result["manifest_shape"] == "vcs-metadata-only"
        assert "VERSION-CONTROL METADATA" in result["note"]
        assert "materialize" in result["note"]

        # Step 0.4 before reading further.
        git(repo, "add", verify_reference.MANIFEST_NAME)
        git(repo, "commit", "-q", "-m", "record observed manifest")

        # §0b.2: materialize the committed tree READ-ONLY — the exact
        # command the playbook commits to, run against the read-only
        # mount (clone only reads the source).
        dest = tmp_path / "ref_materialized"
        git(tmp_path, "clone", "-q", str(ref), str(dest))
        assert (dest / "README.md").read_text() == "# hidden in the object store\n"
        assert (dest / "src" / "model.py").read_text() == "LAYERS = 12\n"

        # §0b.3: pin the surveyed revision — the materialized HEAD is
        # exactly the upstream commit, and ls-tree inventories it.
        assert git(dest, "rev-parse", "HEAD").strip() == head
        listing = git(dest, "ls-tree", "-r", "--long", "HEAD")
        assert "README.md" in listing and "src/model.py" in listing

        # The mount stayed pristine through materialization: the gate
        # re-observes the identical count.
        rc2, result2 = run_gate(monkeypatch, capsys, ref, repo)
        assert result2["observed"]["reference_entry_count"] == count

        # Step 3: re-pin; rc 0 must KEEP the VCS-only warning — a match
        # is not permission to survey metadata as if it were source.
        repin_fingerprint(repo, count, "rehearsal: bare-git remount observed")
        rc, result = run_gate(monkeypatch, capsys, ref, repo)
        assert rc == verify_reference.EXIT_MATCH
        assert "NON-EMPTY" in result["note"]
        assert result["manifest_shape"] == "vcs-metadata-only"
        assert "VERSION-CONTROL METADATA" in result["note"]
    finally:
        chmod_writable_again(ref)


def test_rehearsal_commitless_git_records_negative_result(
    tmp_path, monkeypatch, capsys
):
    """Playbook §0b's fallback branch: a .git with NO commits — the
    closest match to BASELINE.json's description of the upstream. The
    clone of a commit-less repository SUCCEEDS (with a warning) and
    yields an empty working tree, so the playbook's readable-HEAD check
    is the step that must catch it: the failing command output — not
    the absence of working files — is the evidence that the object
    store defines no capabilities."""
    upstream = tmp_path / "upstream"
    upstream.mkdir()
    git(upstream, "init", "-q")  # no commits ever made

    ref = tmp_path / "ref"
    ref.mkdir()
    (upstream / ".git").rename(ref / ".git")
    chmod_read_only(ref)
    try:
        repo = make_fake_repo(tmp_path)

        # The gate still classifies the shape and demands materialization
        # — detection cannot know whether the store holds commits.
        rc, result = run_gate(monkeypatch, capsys, ref, repo)
        assert rc == verify_reference.EXIT_DRIFT
        assert result["manifest_shape"] == "vcs-metadata-only"

        # §0b.2: the clone itself succeeds...
        dest = tmp_path / "ref_materialized"
        clone = git_raw(tmp_path, "clone", "-q", str(ref), str(dest))
        assert clone.returncode == 0
        assert "empty repository" in (clone.stderr + clone.stdout)
        # ...with no working files — which alone proves NOTHING...
        assert not [p for p in dest.iterdir() if p.name != ".git"]
        # ...and the readable-HEAD check is what produces the recordable
        # negative evidence.
        head = git_raw(dest, "log", "-1")
        assert head.returncode != 0
        assert "does not have any commits" in head.stderr
        # The same probe works directly against the read-only mount —
        # and fails for the RIGHT reason (no revision behind HEAD), not
        # a path/permission mistake.
        direct = git_raw(tmp_path, "--git-dir", str(ref / ".git"), "rev-parse", "HEAD")
        assert direct.returncode != 0
        assert "unknown revision" in direct.stderr
    finally:
        chmod_writable_again(ref)
