"""Perf-regression watchdog contracts (arena/obs/regress.py).

The rc semantics over synthetic history lines (the ISSUE 8 acceptance
criterion): rc 1 on an injected 20% throughput regression vs baseline,
rc 0 within tolerance, rc 2 on anything unmeasurable (empty history,
corrupt lines, a pinned metric with no run) — never conflated. The
mutation audit carries a tolerance-comparison-inverted mutant
(regressions pass, improvements fail);
test_watchdog_flags_regressions_not_improvements is its named kill.
"""

import json
import pathlib
import subprocess
import sys

import pytest

from arena.obs import regress

REPO = pathlib.Path(__file__).resolve().parent.parent


def _write_history(path, *lines):
    path.write_text(
        "".join(json.dumps(line) + "\n" for line in lines)
    )
    return path


def _line(value, metric="arena_ingest"):
    return {"metric": metric, "value": value, "unit": "x_vs_cold_repack"}


def _write_baseline(path, metrics):
    path.write_text(json.dumps({"metrics": metrics}))
    return path


def _run(tmp_path, history_lines, metrics, tolerance=None):
    h = _write_history(tmp_path / "hist.jsonl", *history_lines)
    b = _write_baseline(tmp_path / "base.json", metrics)
    argv = ["--history", str(h), "--baseline", str(b)]
    if tolerance is not None:
        argv += ["--tolerance", str(tolerance)]
    return regress.main(argv)


def _report(capsys):
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


# --- the acceptance criterion ----------------------------------------------


def test_watchdog_flags_regressions_not_improvements(tmp_path, capsys):
    """20% throughput drop vs the pin -> rc 1 naming the metric; a
    within-tolerance delta -> rc 0; an IMPROVEMENT of any size -> rc 0
    (the watchdog polices regressions, it never punishes a speedup).
    The audit's inverted-comparison mutant dies on both halves."""
    pin = {"arena_ingest": {"value": 15.0, "direction": "higher",
                            "tolerance": 0.1}}
    assert _run(tmp_path, [_line(12.0)], pin) == 1  # -20% beyond 10%
    report = _report(capsys)
    assert report["verdict"] == "regression"
    assert report["regressions"] == ["arena_ingest"]
    assert report["metrics"]["arena_ingest"]["regressed"] is True
    assert _run(tmp_path, [_line(14.0)], pin) == 0  # -6.7% within 10%
    assert _report(capsys)["verdict"] == "ok"
    assert _run(tmp_path, [_line(40.0)], pin) == 0  # big improvement: ok
    assert _report(capsys)["metrics"]["arena_ingest"]["regressed"] is False


def test_lower_is_better_direction_inverts_the_band(tmp_path, capsys):
    pin = {"arena_soak": {"value": 0.25, "direction": "lower",
                          "tolerance": 0.2}}
    hist = [_line(0.4, metric="arena_soak")]  # +60% latency: regression
    assert _run(tmp_path, hist, pin) == 1
    assert _report(capsys)["regressions"] == ["arena_soak"]
    hist = [_line(0.28, metric="arena_soak")]  # +12% within 20%
    assert _run(tmp_path, hist, pin) == 0
    hist = [_line(0.1, metric="arena_soak")]  # improvement
    assert _run(tmp_path, hist, pin) == 0


def test_regression_exactly_at_tolerance_passes(tmp_path, capsys):
    """The tolerance is the allowance, not the tripwire: a value
    EXACTLY on the band edge passes; epsilon beyond fails. Pow2-exact
    numbers so the boundary is float-exact."""
    pin = {"arena_ingest": {"value": 16.0, "direction": "higher",
                            "tolerance": 0.25}}
    assert _run(tmp_path, [_line(12.0)], pin) == 0  # 16 * 0.75 exactly
    assert _run(tmp_path, [_line(11.999)], pin) == 1
    pin = {"arena_soak": {"value": 0.25, "direction": "lower",
                          "tolerance": 1.0}}
    assert _run(tmp_path, [_line(0.5, metric="arena_soak")], pin) == 0
    assert _run(tmp_path, [_line(0.500001, metric="arena_soak")], pin) == 1
    capsys.readouterr()


def test_newest_run_wins_over_older_history(tmp_path, capsys):
    pin = {"arena_ingest": {"value": 15.0, "direction": "higher",
                            "tolerance": 0.1}}
    # Old runs were bad; the NEWEST is fine -> ok (and vice versa).
    assert _run(tmp_path, [_line(8.0), _line(15.2)], pin) == 0
    assert _run(tmp_path, [_line(15.2), _line(8.0)], pin) == 1
    report = _report(capsys)
    assert report["metrics"]["arena_ingest"]["value"] == 8.0
    assert report["metrics"]["arena_ingest"]["runs_seen"] == 2


# --- noise-aware tolerances -------------------------------------------------


def test_noise_aware_tolerance_derives_from_history_spread(tmp_path, capsys):
    """Without an explicit pin tolerance, the band comes from the
    metric's OWN prior wobble (3x relative stdev, floored): a noisy
    metric tolerates a dip an explicitly-tight pin would flag."""
    pin_noise = {"arena_ingest": {"value": 10.0, "direction": "higher"}}
    noisy = [_line(v) for v in (10.0, 12.0, 8.0, 11.0, 8.0)]
    assert _run(tmp_path, noisy, pin_noise) == 0
    report = _report(capsys)
    entry = report["metrics"]["arena_ingest"]
    assert entry["tolerance_source"] == "history-noise"
    assert entry["tolerance"] > 0.1  # wider than the floor
    # The same final value under an explicit tight pin IS a regression.
    pin_tight = {"arena_ingest": {"value": 10.0, "direction": "higher",
                                  "tolerance": 0.05}}
    assert _run(tmp_path, noisy, pin_tight) == 1
    # Too few priors: the floor applies.
    assert regress.noise_tolerance([10.0, 11.0], 0.1) == 0.1
    assert regress.noise_tolerance([], 0.1) == 0.1


# --- bad input is rc 2, never rc 1 ------------------------------------------


def test_empty_history_is_bad_input(tmp_path, capsys):
    pin = {"arena_ingest": {"value": 15.0, "direction": "higher"}}
    assert _run(tmp_path, [], pin) == 2
    report = _report(capsys)
    assert report["verdict"] == "bad-input"
    assert "empty" in report["error"]


def test_pinned_metric_missing_from_history_is_bad_input(tmp_path, capsys):
    pin = {"arena_serve": {"value": 100.0, "direction": "higher"}}
    assert _run(tmp_path, [_line(15.0)], pin) == 2
    assert "arena_serve" in _report(capsys)["error"]


def test_corrupt_history_line_is_bad_input(tmp_path, capsys):
    h = tmp_path / "hist.jsonl"
    h.write_text(json.dumps(_line(15.0)) + "\nnot json {{{\n")
    b = _write_baseline(
        tmp_path / "base.json",
        {"arena_ingest": {"value": 15.0, "direction": "higher"}},
    )
    assert regress.main(["--history", str(h), "--baseline", str(b)]) == 2
    assert "line 2" in _report(capsys)["error"]


def test_malformed_baseline_is_bad_input(tmp_path, capsys):
    hist = [_line(15.0)]
    bad_pins = [
        {},  # empty metrics
        {"arena_ingest": {"value": "fast", "direction": "higher"}},
        {"arena_ingest": {"value": 15.0, "direction": "up"}},
        {"arena_ingest": {"value": 15.0, "direction": "higher",
                          "tolerance": -0.1}},
    ]
    for pins in bad_pins:
        assert _run(tmp_path, hist, pins) == 2, pins
    assert regress.main(
        ["--history", str(tmp_path / "absent.jsonl"),
         "--baseline", str(tmp_path / "base.json")]
    ) == 2
    capsys.readouterr()


def test_unpinned_history_metrics_are_reported_not_failed(tmp_path, capsys):
    pin = {"arena_ingest": {"value": 15.0, "direction": "higher",
                            "tolerance": 0.1}}
    hist = [_line(15.0), _line(99.0, metric="arena_new_mode")]
    assert _run(tmp_path, hist, pin) == 0
    assert _report(capsys)["unpinned"] == ["arena_new_mode"]


def test_repo_baseline_file_is_valid():
    """The committed BENCH_BASELINE.json (the standing bench gate's
    pin) must always load: every metric numeric, every direction
    legal."""
    doc = regress.load_baseline(REPO / "BENCH_BASELINE.json")
    assert set(doc["metrics"]) == {
        "arena_elo_update_speedup", "arena_ingest", "arena_pipeline",
        "arena_serve", "arena_soak", "arena_frontend", "arena_replica",
        "arena_tenant", "arena_matchloop",
    }
    assert doc["metrics"]["arena_soak"]["direction"] == "lower"
    assert doc["metrics"]["arena_matchloop"]["direction"] == "higher"
    assert doc["metrics"]["arena_tenant"]["direction"] == "higher"
    assert doc["metrics"]["arena_frontend"]["direction"] == "higher"
    assert doc["metrics"]["arena_replica"]["direction"] == "higher"


@pytest.mark.slow
def test_cli_subprocess_contract(tmp_path):
    """The documented operator command end to end:
    `python -m arena.obs.regress` with rc 0 on a healthy history and
    rc 1 on a regressed one (one plain-python spawn, ~1.7s on this
    image — slow-marked with the other subprocess-heavy acceptance
    runs; the in-process tests above cover every branch)."""
    h = _write_history(tmp_path / "hist.jsonl", _line(15.2))
    b = _write_baseline(
        tmp_path / "base.json",
        {"arena_ingest": {"value": 15.0, "direction": "higher",
                          "tolerance": 0.1}},
    )
    proc = subprocess.run(
        [sys.executable, "-m", "arena.obs.regress",
         "--history", str(h), "--baseline", str(b)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout.strip())["verdict"] == "ok"
