"""Incremental-ingest contracts (arena/ingest.py + the engine wiring).

The load-bearing property is EQUIVALENCE: any random split of a match
set into ingest batches must yield the same groupings, the same Elo
ratings, and the same Bradley–Terry strengths as one cold
pack-from-scratch pass — otherwise the incremental speedup would be a
speedup over a different computation. Alongside it, the structural
contracts each mutation-audit mutant polices by name:

- `test_compaction_respects_threshold` — the delta tail stays pending
  below `compact_threshold` (adds stay O(d log d)) and folds exactly
  when the threshold is crossed (mutant: broken threshold comparison);
- `test_galloping_merge_preserves_every_entry` — compaction merges the
  tail, never drops it (mutant: skipped galloping merge);
- `test_chunk_layout_peak_bucket_strictly_smaller_than_pow2` — the
  chunked BT layout's largest bucket stays one chunk, never the
  single pow2 pad (mutant: chunked BT padded back to one bucket).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from arena import engine, ingest
from arena import ratings as R
from arena.analysis import sanitize
from arena.engine import ArenaEngine

P = 40


def make_matches(n, num_players=P, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.integers(0, num_players, n)
    b = (a + 1 + rng.integers(0, num_players - 1, n)) % num_players
    return a.astype(np.int32), b.astype(np.int32)


def random_split(w, l, seed, max_batches=8):
    """Random contiguous split of a match set into ingest batches,
    always including at least one empty batch."""
    rng = np.random.default_rng(seed)
    cuts = np.sort(rng.integers(0, len(w) + 1, rng.integers(1, max_batches)))
    bounds = [0, *cuts.tolist(), len(w)]
    batches = [
        (w[a:b], l[a:b]) for a, b in zip(bounds, bounds[1:])
    ]
    batches.insert(int(rng.integers(0, len(batches) + 1)), (w[:0], l[:0]))
    return batches


def interleaved_keys(w, l):
    keys = np.empty(2 * len(w), np.int32)
    keys[0::2] = w
    keys[1::2] = l
    return keys


def segment_sums_via(csr, values2n):
    perm, bounds = csr.grouping()
    return np.asarray(
        R.sorted_segment_sum(
            jnp.asarray(values2n), jnp.asarray(perm), jnp.asarray(bounds)
        )
    )


# --- the equivalence property (the satellite's named test) -----------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_any_random_split_matches_cold_pack(seed):
    """Property: ingest batches in ANY random split (empty batch
    included) -> grouping segment sums, engine Elo ratings, and BT
    refit strengths all match the single cold pass within tolerance
    (ARENA_BENCH_TOL-style budget, far tighter here)."""
    w, l = make_matches(900, seed=seed)
    batches = random_split(w, l, seed=100 + seed)
    # Grouping: incremental CSR == exact segment sum over the same keys.
    csr = ingest.MergeableCSR(P, compact_threshold=256)
    for bw, bl in batches:
        csr.add(bw, bl)
    assert csr.num_matches == len(w)
    vals = np.repeat(
        np.random.default_rng(seed).normal(size=len(w)).astype(np.float32), 2
    )
    got = segment_sums_via(csr, vals)
    want = np.asarray(
        jax.ops.segment_sum(
            jnp.asarray(vals), jnp.asarray(interleaved_keys(w, l)), num_segments=P
        )
    )
    np.testing.assert_allclose(got, want, atol=1e-4)

    # Elo: ingest path == update path, batch for batch, bit-exact
    # (same jitted function, same packed layout).
    eng_inc, eng_cold = ArenaEngine(P), ArenaEngine(P)
    for bw, bl in batches:
        r_inc = eng_inc.ingest(bw, bl)
        r_cold = eng_cold.update(bw, bl)
    np.testing.assert_array_equal(np.asarray(r_inc), np.asarray(r_cold))

    # BT: chunked refit over the incremental grouping == single-bucket
    # cold fit over the same history.
    chunked = np.asarray(eng_inc.refit_incremental(num_iters=30, chunk_entries=512))
    single = np.asarray(eng_cold.bt_strengths(num_iters=30))
    np.testing.assert_allclose(chunked, single, atol=1e-3)


def test_compaction_boundary_split_is_equivalent():
    """The compaction-boundary case: batch sizes chosen so one add
    lands exactly ON the threshold (no compaction: strict >) and the
    next one crosses it mid-stream — grouping must stay exact across
    the boundary."""
    w, l = make_matches(600, seed=7)
    csr = ingest.MergeableCSR(P, compact_threshold=400)
    csr.add(w[:200], l[:200])  # tail = 400 entries == threshold
    assert csr.compactions == 0 and csr.tail_entries == 400
    csr.add(w[200:201], l[200:201])  # crosses: 402 > 400 -> compacts
    assert csr.compactions == 1 and csr.tail_entries == 0
    csr.add(w[201:], l[201:])
    vals = np.repeat(np.arange(len(w), dtype=np.float32), 2)
    got = segment_sums_via(csr, vals)
    want = np.asarray(
        jax.ops.segment_sum(
            jnp.asarray(vals), jnp.asarray(interleaved_keys(w, l)), num_segments=P
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_empty_batch_is_a_no_op_everywhere():
    eng = ArenaEngine(P)
    before = np.asarray(eng.ratings).copy()
    eng.ingest([], [])
    np.testing.assert_array_equal(np.asarray(eng.ratings), before)
    assert eng.matches_ingested == 0
    csr = ingest.MergeableCSR(P)
    assert csr.add([], []) == 0
    assert csr.num_matches == 0 and csr.tail_entries == 0
    with pytest.raises(ValueError, match="no matches ingested"):
        eng.refit_incremental()


# --- structural contracts (each kills a named mutant) ----------------------


def test_compaction_respects_threshold():
    """Below the threshold the tail stays pending (adds must not pay a
    merge each); one entry past it, the tail folds into the main runs.
    Kills the broken-threshold-comparison mutant in both directions:
    inverted, the first assertion fails (eager compaction); disabled,
    the second does (tail never folds)."""
    csr = ingest.MergeableCSR(P, compact_threshold=100)
    w, l = make_matches(45, seed=3)
    csr.add(w, l)  # 90 entries: under
    assert csr.tail_entries == 90
    assert csr.compactions == 0
    csr.add(w[:10], l[:10])  # 110 > 100: compacts
    assert csr.tail_entries == 0
    assert csr.compactions == 1
    perm, bounds = csr.grouping()
    assert perm.size == 2 * 55 and int(bounds[-1]) == 2 * 55


def test_size_ratio_policy_scales_with_base():
    """The LSM contract: the compaction limit is
    max(compact_threshold, main_entries // size_ratio), so the tail a
    big base tolerates GROWS with the base — merge cost stays
    amortized O(size_ratio) per entry instead of one O(main) merge per
    fixed-size batch. Kills the inverted-size-ratio mutant (min
    collapses the limit back to the floor: the mid-size add below
    would compact)."""
    csr = ingest.MergeableCSR(P, compact_threshold=64, size_ratio=4)
    w, l = make_matches(1000, seed=11)
    csr.add(w, l)  # 2000 entries > floor: compacts during the add
    assert csr.compactions == 1 and csr.tail_entries == 0
    assert csr._compact_limit() == 500  # main/size_ratio beats the floor
    w2, l2 = make_matches(200, seed=12)
    csr.add(w2, l2)  # tail 400 <= 500: pending, even though 400 > floor
    assert csr.compactions == 1
    assert csr.tail_entries == 400
    w3, l3 = make_matches(60, seed=13)
    csr.add(w3, l3)  # tail 520 > 500: folds
    assert csr.compactions == 2
    assert csr.tail_entries == 0
    # Exactness across the policy boundary, same as every other split.
    vals = np.repeat(np.arange(1260, dtype=np.float32), 2)
    got = segment_sums_via(csr, vals)
    allw = np.concatenate([w, w2, w3])
    alll = np.concatenate([l, l2, l3])
    want = np.asarray(
        jax.ops.segment_sum(
            jnp.asarray(vals), jnp.asarray(interleaved_keys(allw, alll)),
            num_segments=P,
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_size_ratio_rejects_degenerate_ratio():
    with pytest.raises(ValueError, match="size_ratio"):
        ingest.MergeableCSR(P, size_ratio=0)


def test_galloping_merge_preserves_every_entry():
    """Compaction must MERGE the delta tail, never drop it: every
    interleaved entry position survives exactly once and the merged
    keys are sorted. Kills the skipped-galloping-merge mutant (which
    silently discards the tail)."""
    csr = ingest.MergeableCSR(P, compact_threshold=64)
    total = 0
    for seed, n in enumerate((40, 11, 90, 5, 64)):
        w, l = make_matches(n, seed=seed)
        csr.add(w, l)
        total += n
    csr.compact()
    perm, bounds = csr.grouping()
    assert np.array_equal(np.sort(perm), np.arange(2 * total))
    assert int(bounds[-1]) == 2 * total
    assert np.array_equal(csr._keys, np.sort(csr._keys))


def test_chunk_layout_peak_bucket_strictly_smaller_than_pow2():
    """The memory-cliff fact, pinned: the chunked layout's largest
    padded buffer is ONE chunk, strictly smaller than the single
    pow2 bucket whenever the set outgrows a chunk. Kills the
    pad-chunked-BT-back-to-one-bucket mutant (whose peak becomes the
    pow2 pad again). The layouts must also agree numerically."""
    n = 3000
    w, l = make_matches(n, seed=9)
    csr = ingest.MergeableCSR(P)
    csr.add(w, l)
    perm, bounds = csr.grouping()
    chunk_entries = 1024
    perms, chunk_bounds = ingest.chunk_layout(perm, bounds, chunk_entries)
    single_entries = 2 * engine.bucket_size(n)
    assert perms.shape[1] < single_entries, (
        f"chunked peak bucket {perms.shape[1]} must be strictly smaller "
        f"than the single-pow2 pad {single_entries}"
    )
    assert perms.shape == (-(-2 * n // chunk_entries), chunk_entries)
    # Sentinel pads point one past the last real entry.
    assert perms.max() == 2 * n
    wc = jnp.asarray(np.bincount(w, minlength=P).astype(np.float32))
    chunked = np.asarray(
        R.jit_bt_fit_chunked(P, num_iters=20)(
            jnp.asarray(w), jnp.asarray(l), jnp.asarray(perms),
            jnp.asarray(chunk_bounds), wc,
        )
    )
    whole = engine.pack_batch(P, w, l, min_bucket=engine.bucket_size(n))
    single = np.asarray(
        R.jit_bt_fit(P, num_iters=20)(
            whole.winners, whole.losers, whole.valid, whole.perm,
            whole.bounds, wc,
        )
    )
    np.testing.assert_allclose(chunked, single, atol=1e-3)


def test_chunk_layout_rejects_degenerate_inputs():
    with pytest.raises(ValueError, match="chunk_entries"):
        ingest.chunk_layout(np.arange(4, dtype=np.int32), np.zeros(3, np.int32), 0)
    with pytest.raises(ValueError, match="empty"):
        ingest.chunk_layout(np.empty(0, np.int32), np.zeros(3, np.int32), 8)


# --- staging: reuse, double buffering, zero recompiles ---------------------


def test_staging_double_buffers_and_stops_allocating():
    """Two slots per bucket, rotated: consecutive stages of the same
    bucket use DIFFERENT host arrays (the in-flight dispatch's source
    is never overwritten), and after both slots exist steady-state
    traffic allocates nothing. Slot lifetime is explicit: stage marks
    in-flight, release() retires the oldest."""
    staging = ingest.StagingBuffers(P, min_bucket=256)
    w, l = make_matches(100, seed=1)
    # Deliberate bare stage()s with slots held in flight across the
    # asserts: the slot mechanics ARE the subject under test here.
    staging.stage(w, l)  # jaxlint: disable=missing-finally-for-paired-call
    assert staging.slots_allocated == 1
    a = staging._rings[256][0]
    staging.stage(w[:50], l[:50])  # jaxlint: disable=missing-finally-for-paired-call
    assert staging.slots_allocated == 2
    b = staging._rings[256][1]
    assert a is not b
    assert staging._next[256] == 0, "third stage must rotate back to slot 0"
    assert staging.in_flight() == 2
    staging.release()  # slot a's dispatch consumed
    assert staging.in_flight() == 1
    for n in (1, 7, 100, 255):
        staging.stage(w[:n], l[:n])
        staging.release()
    assert staging.slots_allocated == 2, "steady state allocated a new slot"
    assert staging.stages == 6


def test_staging_rotation_into_in_flight_slot_raises():
    """The in-flight guard: with both slots of a bucket staged and
    neither released, a third stage must raise (silently overwriting
    the arrays a live dispatch was staged from is the race the packer
    thread would otherwise hit), and release() past empty raises too."""
    staging = ingest.StagingBuffers(P, min_bucket=256)
    w, l = make_matches(20, seed=6)
    # Deliberate: both slots must be held in flight to force the guard.
    staging.stage(w, l)  # jaxlint: disable=missing-finally-for-paired-call
    staging.stage(w, l)  # jaxlint: disable=missing-finally-for-paired-call
    with pytest.raises(RuntimeError, match="in-flight"):
        staging.stage(w, l)
    # Releasing makes the same rotation legal again.
    staging.release()
    staging.stage(w, l)
    staging.release()
    staging.release()
    with pytest.raises(RuntimeError, match="no in-flight"):
        staging.release()


def test_staged_pack_equals_pack_batch():
    """The staged layout is the SAME layout pack_batch computes into
    fresh allocations — bit-for-bit, so ingest() and update() share
    one jit cache entry per bucket."""
    w, l = make_matches(77, seed=4)
    staging = ingest.StagingBuffers(P, min_bucket=256)
    # Deliberately left in flight: the staged arrays are compared below
    # and the buffers object dies with the test.
    staged = staging.stage(w, l)  # jaxlint: disable=resource-leaked-on-exception
    cold = engine.pack_batch(P, w, l, min_bucket=256)
    for got, want in zip(staged[:5], cold[:5]):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert staged.num_real == cold.num_real


def test_steady_state_ingest_causes_zero_recompiles():
    """The acceptance criterion, in-suite: after warmup, arbitrary
    batch sizes through ingest() add ZERO jit-cache entries — asserted
    via RecompileSentinel, and the staging pool stays fixed."""
    eng = ArenaEngine(P)
    w, l = make_matches(engine.MIN_BUCKET, seed=5)
    eng.ingest(w[:10], l[:10])  # warmup: compiles the floor bucket
    eng.ingest(w[:20], l[:20])  # second slot of the same bucket
    sentinel = sanitize.RecompileSentinel(update=eng.num_compiles)
    slots_after_warmup = eng._staging.slots_allocated
    for n in (1, 7, 100, 255, engine.MIN_BUCKET):
        eng.ingest(w[:n], l[:n])
    sentinel.assert_no_new_compiles()
    assert eng._staging.slots_allocated == slots_after_warmup


def test_failed_pack_abandons_the_acquired_slot():
    """The exceptional-path regression the v4 lint audit surfaced: a
    failure between _acquire and the PackedBatch return used to leave
    the slot in flight forever — no dispatch would carry it, so no
    release() would ever retire it, and after `depth` such failures the
    bucket stalled every stage(). The abandon must hit the EXACT slot
    (not the FIFO head, which mid-pack belongs to an older live
    dispatch) and must leave the pool fully usable."""
    staging = ingest.StagingBuffers(P, min_bucket=256)
    w, l = make_matches(40, seed=9)
    # An older dispatch is live: its slot is the FIFO head the failed
    # pack must NOT retire.
    staging.stage(w, l)  # jaxlint: disable=missing-finally-for-paired-call
    head = staging._inflight[0]
    real_argsort = np.argsort

    def exploding_argsort(*args, **kwargs):
        raise MemoryError("synthetic mid-pack failure")

    np.argsort = exploding_argsort
    try:
        with pytest.raises(MemoryError, match="mid-pack"):
            # Deliberate: this stage MUST fail mid-pack — the abandon
            # path is the subject under test.
            staging.stage(w[:10], l[:10])  # jaxlint: disable=missing-finally-for-paired-call
    finally:
        np.argsort = real_argsort
    # The failed stage's slot was abandoned; the live dispatch's was not.
    assert staging.in_flight() == 1
    assert staging._inflight[0] is head
    assert head.in_flight
    # The pool still works: repeated stage/release cycles through the
    # same bucket succeed — the rotation rewound onto the abandoned
    # slot, so no spurious in-flight guard and no permanent stall.
    # (FIFO: the first release retires `head`, the oldest dispatch.)
    # Deliberate bare pairs: the slot mechanics ARE the subject here.
    for n in (10, 40, 200):
        staging.stage(w[:n], l[:n])  # jaxlint: disable=missing-finally-for-paired-call
        staging.release()
    staging.release()
    assert staging.in_flight() == 0


def test_staging_rejects_shallow_depth_and_bad_ids():
    with pytest.raises(ValueError, match="two slots"):
        ingest.StagingBuffers(P, depth=1)
    staging = ingest.StagingBuffers(P)
    with pytest.raises(ValueError, match="player ids"):
        # Validation rejects the batch BEFORE a slot is acquired, so
        # there is nothing to release — statically indistinguishable.
        staging.stage([0, P], [1, 2])  # jaxlint: disable=resource-leaked-on-exception


# --- engine wiring ---------------------------------------------------------


def test_ingest_rejects_bad_batch_without_state_change():
    """Same no-half-ingest contract update() has."""
    eng = ArenaEngine(8)
    eng.ingest([0, 1], [2, 3])
    before = np.asarray(eng.ratings).copy()
    with pytest.raises(ValueError, match="player ids"):
        eng.ingest([0, 8], [1, 2])
    np.testing.assert_array_equal(np.asarray(eng.ratings), before)
    assert eng.matches_ingested == 2


def test_mixed_update_and_ingest_share_one_history():
    """Both paths feed one match store: refits see everything no
    matter which path ingested it."""
    eng = ArenaEngine(P)
    w, l = make_matches(300, seed=8)
    eng.update(w[:100], l[:100])
    eng.ingest(w[100:250], l[100:250])
    eng.update(w[250:], l[250:])
    assert eng.matches_ingested == 300
    chunked = np.asarray(eng.refit_incremental(num_iters=25, chunk_entries=256))
    single = np.asarray(eng.bt_strengths(num_iters=25))
    np.testing.assert_allclose(chunked, single, atol=1e-3)


def test_clone_is_independent():
    csr = ingest.MergeableCSR(P, compact_threshold=64, size_ratio=4)
    w, l = make_matches(50, seed=2)
    csr.add(w, l)
    snap = csr.clone()
    csr.add(w, l)
    assert snap.num_matches == 50 and csr.num_matches == 100
    assert snap.size_ratio == csr.size_ratio
    assert snap.compact_threshold == csr.compact_threshold
    perm, bounds = snap.grouping()
    assert perm.size == 100 and int(bounds[-1]) == 100
