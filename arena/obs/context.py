"""Trace-context propagation: one request's identity across threads.

`arena/obs/tracing.py` records WHERE time went (named spans in a ring);
this module records WHOSE time it was. A `TraceContext` is the tiny
immutable pair `(trace_id, span_id)` — the trace a request belongs to
and the span that should adopt any work done on its behalf — and the
machinery here moves that pair across the two boundaries the pipeline
has:

1. **Within a thread**: a thread-local STACK of contexts. A live span
   pushes its own context on enter and pops on exit, so nested spans
   link parent→child with no caller involvement (`engine.apply` inside
   `pipeline.dispatch` inside a batch root just works). `current()`
   reads the innermost entry; when the stack is empty there is no
   active request and a new span becomes a ROOT of a fresh trace.

2. **Across threads**: contexts are plain values, so a producer
   captures `current()` and ships it along with the work item (the
   ingest queue carries one per raw batch); the consumer wraps its
   processing in `attach(ctx)`, which pushes the foreign context onto
   ITS thread-local stack for the duration. The packer thread's
   `pipeline.pack` span then parents to the producer's `batch.submit`
   span — the cross-thread chain the Chrome export draws flow arrows
   for. `attach(None)` is an explicit no-op (the null-observability
   path never creates contexts, so consumers attach unconditionally).

Deliberately NOT context-var magic: a thread-local list is the whole
mechanism, it is obvious under a debugger, and it costs one attribute
read per span on the hot path. No jax imports (the arena/obs rule),
and no clock reads — this module carries identity, it never times
anything (the jaxlint `timing-without-block` rule has nothing to see
here; the tier-1 lint test pins that an `attach`-wrapped dispatch
lints clean).
"""

import threading
from typing import NamedTuple


class TraceContext(NamedTuple):
    """One request's identity: the trace it belongs to and the span new
    work should parent to. Plain value — safe to ship across threads
    inside queue items."""

    trace_id: int
    span_id: int


_local = threading.local()


def _stack():
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def current():
    """The innermost active context on THIS thread, or None when no
    span (and no attach) is live — in which case the next span opened
    here becomes the root of a fresh trace."""
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


def push(ctx):
    """Make `ctx` the current context (span enter / attach enter)."""
    _stack().append(ctx)
    return ctx


def pop():
    """Undo the matching `push` (span exit / attach exit)."""
    _stack().pop()


class attach:
    """Adopt a context captured on another thread for a `with` block.

    The consumer half of cross-thread propagation: work done inside the
    block parents to `ctx.span_id` and joins `ctx.trace_id`. `ctx` may
    be None (nothing was live when the producer captured — the null
    path), making the block a no-op; consumers attach unconditionally
    instead of branching.
    """

    __slots__ = ("_ctx",)

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        if self._ctx is not None:
            push(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb):
        if self._ctx is not None:
            pop()
        return False
