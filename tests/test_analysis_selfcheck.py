"""jaxlint v2 self-check: the tier-1 gate over the whole tree.

Three mechanical invariants, run on every suite pass:

1. The FULL v2 engine (two-pass symbol table + all rules, concurrency
   rules included) reports ZERO findings over the repo's own tree —
   and that pass is not vacuous: the four production modules carry
   real `guarded_by` annotations the engine demonstrably sees.
2. Every registered rule fires at least once on the embedded
   bad-example corpus — a rule that cannot fire is dead weight that
   reads as protection.
3. Every rule name in README's rule table exists in the registry and
   vice versa — the doc/code drift tripwire (the table is the operator
   contract; a renamed rule must update it in the same commit).
"""

import pathlib
import re

from arena.analysis import jaxlint, project

REPO = pathlib.Path(__file__).resolve().parent.parent
CORPUS = REPO / "arena" / "analysis" / "badcorpus"

CONCURRENCY_RULES = {
    "unguarded-shared-write",
    "blocking-while-locked",
    "lock-order-inversion",
    "thread-no-liveness-recheck",
}

# jaxlint v3: the abstract-interpretation families.
ABSINT_RULES = {
    "unbucketed-shape-at-jit-boundary",
    "dtype-drift-into-kernel",
    "unvalidated-wire-input",
}

# jaxlint v4: the lifecycle/resource typestate analyzer.
LIFECYCLE_RULES = {
    "resource-leaked-on-exception",
    "use-after-close",
    "lock-held-across-raise",
    "missing-finally-for-paired-call",
}

# jaxlint v5: the interprocedural effect-contract analyzer.
EFFECTS_RULES = {
    "nondeterminism-in-deterministic-fn",
    "hidden-state-read-in-pure-render",
    "check-then-act-race",
    "undeclared-mutation-in-contract",
}

# jaxlint v6: the serialized-schema contract analyzer.
SCHEMA_RULES = {
    "schema-drift-without-version-bump",
    "reader-writer-schema-mismatch",
    "undeclared-serialized-field",
    "replication-boundary-write",
}


def test_full_tree_lints_clean_with_concurrency_rules_active():
    """The acceptance criterion: `python -m arena.analysis` over the
    clean tree reports 0 findings WITH the four concurrency rules, the
    three v3 abstract-interpretation families, the four v4 lifecycle
    rules, the four v5 effect-contract rules, AND the four v6
    serialized-schema rules registered — the real
    guarded_by annotations, the real bucketing/validator call sites,
    the real `# protocol:` contracts, and the real `# deterministic` /
    `# pure-render` contracts all in place. Runs with jobs=2: the
    26-rule pass stays fast, and the parallel path is exercised on
    every suite run (bit-identity to serial is pinned in
    test_analysis_lint.py)."""
    assert CONCURRENCY_RULES <= set(jaxlint.RULES)
    assert ABSINT_RULES <= set(jaxlint.RULES)
    assert LIFECYCLE_RULES <= set(jaxlint.RULES)
    assert EFFECTS_RULES <= set(jaxlint.RULES)
    assert SCHEMA_RULES <= set(jaxlint.RULES)
    findings = jaxlint.lint_paths(jaxlint.default_targets(), jobs=2)
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


def test_every_registered_rule_declares_a_severity():
    """The --format=json `severity` field is only as stable as the
    registry behind it: every rule must declare one of the closed
    severity vocabulary (no default exists — a new rule without one
    fails at registration, and this test pins the vocabulary)."""
    assert jaxlint.SEVERITIES == ("error", "warning")
    for name, r in jaxlint.RULES.items():
        assert r.severity in jaxlint.SEVERITIES, (
            f"rule {name!r} declares severity {r.severity!r}"
        )


def test_clean_pass_is_not_vacuous():
    """The zero-findings pass above only means something if the engine
    actually SEES guarded state in the production modules: assert the
    symbol table collects non-empty guarded contracts from all four."""
    annotated = {
        "arena/ingest.py": "MergeableCSR",
        "arena/pipeline.py": "IngestPipeline",
        "arena/obs/metrics.py": "Histogram",
        "arena/net/frontdoor.py": "FrontDoor",
    }
    for rel, cls_name in annotated.items():
        path = REPO / rel
        ctx = jaxlint.ModuleContext(str(path), path.read_text())
        cls = ctx.symbols.classes[cls_name]
        assert cls.guarded, f"{rel}: {cls_name} lost its guarded_by contract"
        assert cls.lock_attrs, f"{rel}: {cls_name} lost its lock attrs"
    # ...and (v4) the lifecycle pass demonstrably sees the real
    # `# protocol:` contracts: paired, terminal-only, and ops-plane.
    protocols = {
        "arena/ingest.py": ("StagingBuffers", [("stage", "release")], set()),
        "arena/engine.py": ("ArenaEngine", [], {"shutdown"}),
        "arena/obs/__init__.py": (
            "Observability", [("start_ops", "stop_ops")], set(),
        ),
        # PR 18: the replica catch-up resources are lifecycle-contracted
        # — the reader pairs start with close, the cursor owns a wire
        # connection it must release.
        "arena/net/replica.py": (
            "ReplicaReader", [("start", "close")], set(),
        ),
        # PR 20: the matchmaker's close is terminal-only (it drops the
        # presence gauge; the jit cache needs no teardown).
        "arena/match/matchmaker.py": ("Matchmaker", [], {"close"}),
    }
    for rel, (cls_name, pairs, terminal) in protocols.items():
        path = REPO / rel
        ctx = jaxlint.ModuleContext(str(path), path.read_text())
        cls = ctx.symbols.classes[cls_name]
        assert cls.has_protocols(), f"{rel}: {cls_name} lost its protocol"
        assert cls.protocol_pairs == pairs, f"{rel}: {cls_name} pairs drifted"
        assert cls.protocol_terminal >= terminal, (
            f"{rel}: {cls_name} terminal methods drifted"
        )
    replica_path = REPO / "arena/net/replica.py"
    replica_ctx = jaxlint.ModuleContext(
        str(replica_path), replica_path.read_text()
    )
    cursor = replica_ctx.symbols.classes["SegmentCursor"]
    assert cursor.has_protocols(), "SegmentCursor lost its close protocol"
    assert "close" in cursor.protocol_methods()
    # ...and (v5) the effect pass demonstrably sees the real
    # `# deterministic` / `# pure-render` contracts on the apply and
    # render paths — the annotations ROADMAP items 1 and 2 lean on.
    contracts = {
        "arena/engine.py": {
            "ArenaEngine.update": "deterministic",
            "ArenaEngine.ingest": "deterministic",
        },
        "arena/net/frontdoor.py": {
            "FrontDoor._apply": "deterministic",
            "FrontDoor._pop_next_locked": "deterministic",
        },
        "arena/ratings.py": {
            "elo_batch_update_sorted": "deterministic",
            "elo_epoch": "deterministic",
            "bt_fit": "deterministic",
        },
        "arena/serving.py": {
            "write_snapshot": "deterministic",
            "read_snapshot_chain": "deterministic",
            "ArenaServer._player_row": "pure_render",
        },
        # PR 18: the replica replay path is `# deterministic` — the
        # static face of bit-exact log replay.
        "arena/net/replica.py": {
            "ReplicaReader._apply_records": "deterministic",
        },
        # PR 20: proposal selection is deterministic at a fixed view
        # (watermark-seeded RNG), and the /match payload is a pure
        # render off that view.
        "arena/match/matchmaker.py": {
            "pair_components": "deterministic",
            "propose_pairs": "deterministic",
            "render_match_payload": "pure_render",
        },
    }
    for rel, expected in contracts.items():
        path = REPO / rel
        ctx = jaxlint.ModuleContext(str(path), path.read_text())
        for qualname, kind in expected.items():
            contract = ctx.symbols.contracts.get(qualname)
            assert contract is not None, (
                f"{rel}: {qualname} lost its effect contract"
            )
            if kind == "deterministic":
                assert contract["deterministic"], (
                    f"{rel}: {qualname} no longer `# deterministic`"
                )
            else:
                assert contract["pure_render"] == "view", (
                    f"{rel}: {qualname} no longer `# pure-render(view)`"
                )
    # ...and (v6) the schema pass demonstrably sees the real
    # `# schema:` contracts on the snapshot, wire, and replication-log
    # writers — the shapes the sidecar registry pins.
    schemas = {
        "arena/serving.py": {
            "write_snapshot": ("arena-snapshot", 3),
            "_validate_chain_link": ("incremental-manifest", 2),
            "ArenaServer._player_row": ("wire-player-row", 1),
        },
        "arena/net/protocol.py": {
            "make_response": ("wire-envelope", 1),
            "parse_submit_body": ("wire-submit-request", 1),
        },
        "arena/net/frontdoor.py": {
            "FrontDoor._apply": ("applied-log-record", 1),
        },
        # PR 18: the /log writer and the replica-side cursor read/write
        # the same recorded shape — sidecar wire-log-segment.
        "arena/net/server.py": {
            "_log_payload": ("wire-log-segment", 1),
        },
        "arena/net/replica.py": {
            "SegmentCursor.fetch": ("wire-log-segment", 1),
        },
        # PR 20: the /match payload renderer — sidecar wire-match.
        "arena/match/matchmaker.py": {
            "render_match_payload": ("wire-match", 1),
        },
    }
    for rel, expected in schemas.items():
        path = REPO / rel
        ctx = jaxlint.ModuleContext(str(path), path.read_text())
        for qualname, declared in expected.items():
            assert ctx.symbols.schemas.get(qualname) == declared, (
                f"{rel}: {qualname} lost its `# schema:` contract"
            )


def test_every_registered_rule_fires_on_the_corpus():
    findings = jaxlint.lint_paths([str(CORPUS)])
    fired = {f.rule for f in findings}
    assert fired == set(jaxlint.RULES), (
        f"rules never exercised by the corpus: {set(jaxlint.RULES) - fired}"
    )


def test_readme_rule_table_matches_registry():
    """Parse the rule table in README's 'Analysis & sanitizers'
    section: its rule names and the live registry must be EQUAL sets —
    a rule documented but not registered is as red as one registered
    but undocumented."""
    readme = (REPO / "README.md").read_text()
    start = readme.index("## Analysis & sanitizers")
    rest = readme[start:]
    next_heading = rest.find("\n## ", 1)
    section = rest if next_heading == -1 else rest[:next_heading]
    documented = set(
        re.findall(r"^\|\s*`([a-z][a-z0-9-]*)`\s*\|", section, re.MULTILINE)
    )
    assert documented, "README rule table not found (parse contract broken)"
    assert documented == set(jaxlint.RULES), (
        f"doc/code drift: only in README {documented - set(jaxlint.RULES)}, "
        f"only in registry {set(jaxlint.RULES) - documented}"
    )


def test_project_table_covers_every_default_target_module():
    """The two-pass driver builds ONE table over the default targets;
    spot-check it resolves the repo's own modules by their import
    names (the suffix-tolerant lookup the cross-module rules use)."""
    contexts = [
        jaxlint.ModuleContext(str(f), f.read_text())
        for f in jaxlint.iter_python_files(jaxlint.default_targets())
    ]
    table = project.ProjectTable([c.symbols for c in contexts])
    for name in ("arena.ingest", "arena.pipeline", "arena.net.frontdoor",
                 "arena.net.replica", "arena.obs.metrics", "arena.sharding",
                 "arena.match.matchmaker"):
        assert table.module(name) is not None, f"table lost {name}"
    # The sharding module's mesh is resolvable by name — what item 3's
    # multi-host modules will import.
    sharding = table.module("arena.sharding")
    assert sharding.meshes or sharding.has_mesh
