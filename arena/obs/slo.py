"""Declarative SLOs with multi-window burn-rate alerting.

The SRE playbook's alerting math over `arena/obs/windows.py` views:
an SLO declares an objective (availability target, or a latency
threshold met by a target fraction of requests), the engine computes
the window's error fraction, and

    burn rate = error_fraction / (1 - target)

i.e. "how many times faster than budget are we burning". Burn 1.0
exhausts the error budget exactly at the window's end; the default
alert threshold of 14.4 is the classic fast-burn page (at 14.4x a
99.9% budget, a 30-day budget dies in ~2 days). An alert FIRES only
when BOTH windows agree:

- the **fast** window (default: the newest ring interval) says the
  burn is happening *now* — so alerts clear quickly once the cause
  stops, and
- the **slow** window (the full ring) says enough budget actually
  burned to matter — so a single bad second cannot page.

Alert transitions are edge-triggered events in the bounded
`Observability.events` log, carrying the trace-id exemplar of the
offending histogram bucket (PR 7's exemplars make "show me the trace
that burned the budget" a dict lookup, resolved via
`Tracer.trace(id)`). `ArenaServer.stats()` embeds `evaluate()` as its
`slo` block, `/debug/slo` serves it over the wire, and the frontend
bench hard-gates both directions: the forced-overload phase MUST fire
the delivery alert (with a resolvable exemplar) and the steady-state
phase MUST stay silent.

Evaluation is pull-based (each `evaluate()` reads the windows fresh);
there is no alerting thread to die. `NullSLOEngine` is the no-op
twin. No jax imports in this package.
"""

import threading

import numpy as np

from arena.obs.windows import _label_match

# The classic fast-burn page threshold (Google SRE workbook chapter 5):
# 14.4x budget burn = a 30-day 99.9% budget gone in ~2 days.
DEFAULT_BURN_THRESHOLD = 14.4
DEFAULT_FAST_INTERVALS = 1

# Bounded per-engine record of firing transitions (the bench gate's
# read; the full stream also lands in Observability.events).
_FIRING_LOG_CAP = 64


class SLOError(ValueError):
    """Malformed SLO declaration."""


class Selector:
    """Names the metric series an SLO term reads: a metric name plus a
    label `match` dict (values ending in ``*`` are prefix patterns,
    e.g. ``{"status": "5*"}``)."""

    __slots__ = ("name", "match")

    def __init__(self, name, match=None):
        self.name = name
        self.match = dict(match) if match else {}

    def to_payload(self):
        return {"metric": self.name, "match": self.match}


class SLO:
    """One declarative objective.

    Availability kind: `good`/`bad` counter selectors;
    error fraction = bad / (good + bad).

    Latency kind: a `latency` histogram selector plus `threshold_s`;
    error fraction = fraction of windowed observations in buckets
    whose upper bound exceeds the threshold (the threshold rounds UP
    to the containing log2 bucket bound, consistent with the
    histogram's conservative percentile semantics).

    `exemplar` optionally names the histogram whose worst bucket's
    trace-id exemplar rides along on alert transitions (defaults to
    the latency selector for latency SLOs).
    """

    __slots__ = ("name", "target", "kind", "good", "bad", "latency",
                 "threshold_s", "exemplar", "burn_threshold",
                 "fast_intervals")

    def __init__(self, name, target, *, good=None, bad=None, latency=None,
                 threshold_s=None, exemplar=None,
                 burn_threshold=DEFAULT_BURN_THRESHOLD,
                 fast_intervals=DEFAULT_FAST_INTERVALS):
        if not 0.0 < target < 1.0:
            raise SLOError(f"SLO {name!r}: target must be in (0, 1), "
                           f"got {target}")
        if latency is not None:
            if threshold_s is None or good is not None or bad is not None:
                raise SLOError(
                    f"SLO {name!r}: latency kind takes latency= + "
                    "threshold_s= and nothing else"
                )
            self.kind = "latency"
        elif good is not None and bad is not None:
            self.kind = "availability"
        else:
            raise SLOError(
                f"SLO {name!r}: declare either latency=+threshold_s= or "
                "good=+bad="
            )
        if burn_threshold <= 0:
            raise SLOError(f"SLO {name!r}: burn_threshold must be > 0")
        self.name = name
        self.target = float(target)
        self.good = good
        self.bad = bad
        self.latency = latency
        self.threshold_s = threshold_s
        self.exemplar = exemplar if exemplar is not None else latency
        self.burn_threshold = float(burn_threshold)
        self.fast_intervals = int(fast_intervals)

    def error_fraction(self, delta):
        """(error_fraction, event_total) over one `WindowDelta`. An
        empty window is a 0.0 error fraction — no traffic burns no
        budget."""
        if self.kind == "availability":
            good = delta.counter_delta(self.good.name, self.good.match)
            bad = delta.counter_delta(self.bad.name, self.bad.match)
            total = good + bad
            return (bad / total if total > 0 else 0.0), total
        h = delta.histogram(self.latency.name, self.latency.match)
        if h.count == 0 or h.bounds.size == 0:
            return 0.0, 0
        # Observations at or under the threshold's bucket bound count
        # as good (le semantics: the threshold rounds up to its bucket).
        idx = int(np.searchsorted(h.bounds, self.threshold_s, side="left"))
        good = int(h.counts[: idx + 1].sum())
        return 1.0 - good / h.count, h.count

    def to_payload(self):
        out = {"name": self.name, "kind": self.kind, "target": self.target,
               "burn_threshold": self.burn_threshold,
               "fast_intervals": self.fast_intervals}
        if self.kind == "latency":
            out["latency"] = self.latency.to_payload()
            out["threshold_s"] = self.threshold_s
        else:
            out["good"] = self.good.to_payload()
            out["bad"] = self.bad.to_payload()
        return out


def default_slos():
    """The serving tier's stock objectives:

    - **wire-availability**: 99.9% of wire requests answer non-5xx
      (4xx are the client's error budget, not ours — excluded).
    - **wire-read-latency**: 99% of wire requests answer within 250ms
      (generous on purpose: it pages on collapse, not on noise).
    - **submit-delivery**: 99.9% of submitted matches reach the
      engine rather than being shed/dropped; the exemplar rides the
      shed-magnitude histogram so the alert names a trace that was
      actually dropped.
    """
    return [
        SLO(
            "wire-availability",
            target=0.999,
            good=Selector("arena_http_requests_total",
                          match={"status": "2*"}),
            bad=Selector("arena_http_requests_total",
                         match={"status": "5*"}),
            exemplar=Selector("arena_http_request_latency_seconds"),
        ),
        SLO(
            "wire-read-latency",
            target=0.99,
            latency=Selector("arena_http_request_latency_seconds"),
            threshold_s=0.25,
        ),
        SLO(
            "submit-delivery",
            target=0.999,
            good=Selector("arena_ingest_matches_total"),
            bad=Selector("arena_pipeline_dropped_matches_total"),
            exemplar=Selector("arena_shed_batch_matches"),
        ),
    ]


# Default replica-staleness threshold: how many matches a replica may
# trail the writer before a staleness observation burns error budget.
# Generous like the stock latency SLO — it pages on a stuck tail, not
# on one slow poll.
DEFAULT_REPLICA_STALENESS_MATCHES = 10_000


# Proposal scoring is one bucketed kernel call plus a triangle argsort
# off an already-built view: a quarter second is a stuck tail, not a
# busy one.
DEFAULT_MATCH_PROPOSAL_LATENCY_S = 0.25


def match_proposal_latency_slo(threshold_s=DEFAULT_MATCH_PROPOSAL_LATENCY_S,
                               target=0.99):
    """The matchmaking plane's burn-rate objective: 99% of /match
    proposal computations (recorded into
    `arena_match_proposal_latency_seconds` by `Matchmaker.propose`)
    must finish within `threshold_s`. Registered by the `Matchmaker`
    constructor via `SLOEngine.add`, so it appears on /debug/slo only
    where a matchmaker is actually attached — and the matchloop soak
    hard-gates on it never firing."""
    return SLO(
        "match-proposal-latency",
        target=target,
        latency=Selector("arena_match_proposal_latency_seconds"),
        threshold_s=float(threshold_s),
    )


def replica_staleness_slo(threshold_matches=DEFAULT_REPLICA_STALENESS_MATCHES,
                          target=0.99):
    """Per-replica staleness as a burn-rate objective: 99% of the
    replica's staleness observations (one per catch-up poll, recorded
    into `arena_replica_staleness_matches`) must be within
    `threshold_matches` of the writer. The latency-SLO math is
    generic over any histogram — here the "latency" is a lag measured
    in matches, not seconds. Registered by `ReplicaReader.start()` via
    `SLOEngine.add`, so it appears on /debug/slo only where a replica
    actually runs — the health surface a fleet controller polls."""
    return SLO(
        "replica-staleness",
        target=target,
        latency=Selector("arena_replica_staleness_matches"),
        threshold_s=float(threshold_matches),
    )


class SLOEngine:
    """Evaluates a set of SLOs against one `SlidingWindow`, tracking
    per-objective ok/firing state and posting edge-triggered
    `slo_alert` events (with exemplar trace ids) into the bounded
    event log."""

    def __init__(self, window, slos=None, obs=None):
        self._window = window
        self._obs = obs
        self.slos = list(slos) if slos is not None else default_slos()
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise SLOError(f"duplicate SLO names: {names}")
        self._lock = threading.Lock()
        self._state = {s.name: "ok" for s in self.slos}  # guarded_by: _lock
        self._fired = {s.name: 0 for s in self.slos}  # guarded_by: _lock
        self._firing_log = []  # guarded_by: _lock (bounded, newest last)
        self.evaluations = 0  # guarded_by: _lock  (pulls, ever)

    def add(self, slo):
        """Register one more objective on a LIVE engine — how a
        component that exists only in some deployments (a replica's
        staleness objective) joins the burn-rate loop without the
        stock list carrying it everywhere. Duplicate names are a
        config error, same as at construction."""
        with self._lock:
            if any(s.name == slo.name for s in self.slos):
                raise SLOError(f"duplicate SLO name: {slo.name!r}")
            self.slos.append(slo)
            self._state[slo.name] = "ok"
            self._fired[slo.name] = 0

    def _exemplar_for(self, slo):
        """The trace-id exemplar of the offending bucket: the p99
        exemplar of the SLO's exemplar histogram, read from the LIVE
        registry (exemplars are latest-wins, so this is the newest
        trace through the worst bucket)."""
        sel = slo.exemplar
        if sel is None or self._obs is None:
            return None
        for (name, lkey), metric in self._obs.registry._sorted_metrics():
            if name != sel.name or not hasattr(metric, "exemplar"):
                continue
            if not _label_match(dict(lkey), sel.match):
                continue
            ex = metric.exemplar(0.99)
            if ex:
                return ex
        return None

    def evaluate(self):  # schema: wire-debug-slo@v1
        """One pull: read the fast and slow windows, compute burn
        rates, transition alert states, return the `slo` block."""
        slow = self._window.delta()
        fast_cache = {}
        objectives = {}
        transitions = []
        with self._lock:
            self.evaluations += 1
            for slo in self.slos:
                k = slo.fast_intervals
                if k not in fast_cache:
                    fast_cache[k] = self._window.delta(intervals=k)
                frac_slow, events_slow = slo.error_fraction(slow)
                frac_fast, events_fast = slo.error_fraction(fast_cache[k])
                budget = 1.0 - slo.target
                burn_slow = frac_slow / budget
                burn_fast = frac_fast / budget
                firing = (
                    burn_fast >= slo.burn_threshold
                    and burn_slow >= slo.burn_threshold
                )
                state = "firing" if firing else "ok"
                prev = self._state[slo.name]
                exemplar = None
                if state != prev:
                    self._state[slo.name] = state
                    exemplar = self._exemplar_for(slo)
                    record = {
                        "slo": slo.name,
                        "state": state,
                        "burn_fast": round(burn_fast, 3),
                        "burn_slow": round(burn_slow, 3),
                        "trace_id": (exemplar or {}).get("trace_id", 0),
                        "exemplar": exemplar,
                    }
                    if state == "firing":
                        self._fired[slo.name] += 1
                        self._firing_log.append(record)
                        del self._firing_log[:-_FIRING_LOG_CAP]
                    transitions.append(record)
                objectives[slo.name] = {
                    "kind": slo.kind,
                    "target": slo.target,
                    "burn_threshold": slo.burn_threshold,
                    "error_frac_fast": round(frac_fast, 6),
                    "error_frac_slow": round(frac_slow, 6),
                    "burn_fast": round(burn_fast, 3),
                    "burn_slow": round(burn_slow, 3),
                    "events_fast": events_fast,
                    "events_slow": events_slow,
                    "state": state,
                    "fired_total": self._fired[slo.name],
                }
            alerts_active = sum(
                1 for s in self._state.values() if s == "firing"
            )
            fired_total = sum(self._fired.values())
        # Event posting happens outside the engine lock (the deque is
        # its own synchronization; no lock nesting to order).
        if self._obs is not None:
            for record in transitions:
                self._obs.event("slo_alert", **record)
        return {
            "objectives": objectives,
            "alerts_active": alerts_active,
            "alerts_fired_total": fired_total,
            "window_s": round(slow.elapsed_s, 3),
        }

    def alerts_fired(self, name=None):
        """Sticky count of ok->firing transitions (one objective, or
        all) — what the bench's silent-at-steady-state gate reads."""
        with self._lock:
            if name is not None:
                return self._fired.get(name, 0)
            return sum(self._fired.values())

    def firings(self, name=None):
        """The recorded firing transitions (newest last), optionally
        filtered to one objective — the bench's must-fire gate reads
        the exemplar trace id off these."""
        with self._lock:
            return [
                dict(r)
                for r in self._firing_log
                if name is None or r["slo"] == name
            ]


class NullSLOEngine:
    """No-op twin: no objectives, never fires, constant-time."""

    enabled = False
    slos = ()
    evaluations = 0

    def add(self, slo):
        return None

    def evaluate(self):
        return {"objectives": {}, "alerts_active": 0,
                "alerts_fired_total": 0, "window_s": 0.0}

    def alerts_fired(self, name=None):
        return 0

    def firings(self, name=None):
        return []
