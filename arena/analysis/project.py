"""Cross-module symbol table: pass 1 of the jaxlint v2 two-pass driver.

jaxlint v1 analyzed one file at a time, so anything defined elsewhere —
a mesh imported from another module, a lock shared across classes — was
invisible, and the rules either stayed quiet (sharding-spec-arity on an
imported mesh) or could not exist at all (lock-order inversion is a
property of the PROJECT, not a file). This module is the fix: one pass
over every file being linted builds a `ProjectTable` mapping

    module -> classes / functions / meshes / locks / assigned attributes

with `from x import y` and `import x.y as z` attribute chains resolved
against the table, and pass 2 (the rules in `jaxlint.py` and
`concurrency.py`) runs with that table in scope via
`ModuleContext.project`.

Conventions the table understands (all stdlib `ast` + `tokenize`, no
imports executed, no jax anywhere):

- **Module names** are derived from the filesystem: walk up from the
  file while `__init__.py` is present, so `arena/ingest.py` is
  `arena.ingest` whether the lint target was `arena/` or the repo root.
  Import resolution is suffix-tolerant (`ProjectTable.module`) so a
  fixture rooted elsewhere still resolves.
- **Meshes**: `name = Mesh(..., (AXES,))` assignments, axis names
  resolved through string constants exactly as the v1 rule did — but
  now recorded per NAME so `from meshes import mesh` in another module
  resolves to the defining module's axis set.
- **Locks**: `self._x = threading.Lock()/RLock()/Condition()` class
  attributes and module-level `NAME = threading.Lock()` globals. Lock
  IDENTITY is the dotted `module.Class.attr` (or `module.NAME`) string,
  so the same lock acquired from two modules unifies in the project's
  lock-order graph.
- **`# guarded_by: <lockname>`** comments on `self.attr = ...`
  assignment lines declare the concurrency contract the
  `unguarded-shared-write` rule enforces: every later write to that
  attribute must happen while holding `self.<lockname>` (lexically
  inside `with self.<lockname>:`, or in a method whose name ends in
  `_locked` — the repo's called-with-lock-held convention).
- **Lock-order edges**: for every `with` acquiring lock B lexically
  inside a held lock A, the edge (A, B) is recorded; calls made while
  holding a lock are recorded too and resolved one level deep through
  the table (same-class methods, module functions, `from x import f`)
  so a with-block that calls into another module's locking code still
  contributes edges. Inconsistent orderings across the whole table are
  the `lock-order-inversion` rule's findings.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

# The annotation convention: `self.attr = ...  # guarded_by: _lock`.
GUARDED_BY_RE = re.compile(r"guarded_by:\s*([A-Za-z_][A-Za-z0-9_]*)")

# The lifecycle convention (jaxlint v4), mirroring guarded_by: a
# `# protocol: stage->release` / `# protocol: close` comment on the
# DEFINING class declares its resource protocol. `a->b` is a paired
# protocol (each call to `a` creates an obligation discharged by `b`);
# a bare method name is a terminal protocol (after calling it, other
# method calls on the object are use-after-close). Multiple specs may
# share one comment, comma-separated.
PROTOCOL_RE = re.compile(r"protocol:\s*(.+)")


def parse_protocols(comment_text):
    """(pairs, terminal) parsed from one comment's text: pairs is a
    list of (acquire, release) method-name tuples, terminal a set of
    method names. Malformed specs are skipped, never a parse error."""
    match = PROTOCOL_RE.search(comment_text)
    if not match:
        return [], set()
    pairs, terminal = [], set()
    for spec in match.group(1).split(","):
        spec = spec.strip()
        if "->" in spec:
            a, _, b = spec.partition("->")
            a, b = a.strip(), b.strip()
            if a.isidentifier() and b.isidentifier():
                pairs.append((a, b))
        elif spec.isidentifier():
            terminal.add(spec)
    return pairs, terminal

# The effect-contract vocabulary (jaxlint v5), mirroring protocol: a
# comment on a DEF header declares the function's contract. Clauses are
# `;`-separated so one comment can carry a contract plus an allowance:
#
#     # deterministic
#     # deterministic; mutates: _store, ratings
#     # pure-render(view)
#
# `deterministic` promises same inputs => bit-identical outputs and
# state writes (no wall clock, unseeded RNG, set/popitem iteration
# order, id(), os.environ, or thread identity flowing into results or
# writes, checked through the call-graph fixpoint closure by
# `effects.py`). `pure-render(NAME)` promises the result depends only
# on the parameters and the named immutable view argument. `mutates:`
# lists the self attributes / module globals the closure is ALLOWED to
# write. The clause anchors (`^` or `;`) keep prose comments that
# merely contain the word "deterministic" from becoming contracts.
DETERMINISTIC_RE = re.compile(r"(?:^|;)\s*deterministic\s*(?:$|;)")
PURE_RENDER_RE = re.compile(
    r"(?:^|;)\s*pure-render\(\s*([A-Za-z_][A-Za-z0-9_]*)\s*\)\s*(?:$|;)"
)
MUTATES_RE = re.compile(
    r"(?:^|;)\s*mutates:\s*"
    r"([A-Za-z_][A-Za-z0-9_]*(?:\s*,\s*[A-Za-z_][A-Za-z0-9_]*)*)"
)


# `schema: <name>@v<N>` marks a def/class as a writer or reader of the
# named serialized format (snapshot manifest, wire envelope, spill
# record, ...). The recorded shape lives in a checked-in sidecar JSON
# under arena/analysis/schemas/ (see schema.py); the clause coexists
# with the other contract clauses on one comment
# (`# pure-render(view); schema: wire-player-row@v1`).
SCHEMA_RE = re.compile(
    r"(?:^|;)\s*schema:\s*([A-Za-z][A-Za-z0-9_.-]*)@v(\d+)\s*(?:$|;)"
)


def parse_schema(comment_text):
    """(name, version) from one comment's `schema:` clause, or None.
    Malformed clauses are simply not matched — never a parse error."""
    match = SCHEMA_RE.search(comment_text)
    if match is None:
        return None
    return match.group(1), int(match.group(2))


def parse_contract(comment_text):
    """A contract record parsed from one comment's text, or None when
    the comment declares nothing. The record is a dict with keys
    `deterministic` (bool), `pure_render` (view parameter name or
    None), and `mutates` (frozenset of allowed write names, meaningful
    only alongside a contract). Malformed clauses are simply not
    matched — never a parse error."""
    deterministic = bool(DETERMINISTIC_RE.search(comment_text))
    render = PURE_RENDER_RE.search(comment_text)
    mutates = MUTATES_RE.search(comment_text)
    if not deterministic and render is None:
        return None
    allowed = frozenset()
    if mutates is not None:
        allowed = frozenset(
            name.strip() for name in mutates.group(1).split(",")
        )
    return {
        "deterministic": deterministic,
        "pure_render": render.group(1) if render is not None else None,
        "mutates": allowed,
    }


# threading constructors whose assignment makes an attribute "a lock"
# (a Condition wraps a lock; acquiring it IS acquiring the lock).
LOCK_FACTORY_TAILS = frozenset({"Lock", "RLock", "Condition", "Semaphore"})

# Methods whose names end with this suffix are the repo's
# called-with-the-lock-held convention (`_add_locked`, `_shed_locked`):
# their bodies are treated as held regions for every class lock.
LOCKED_SUFFIX = "_locked"


def dotted(node) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def module_name_for(path: str) -> str:
    """Dotted module name from the filesystem: walk up while the parent
    holds an `__init__.py`, so the name matches how the repo's own
    imports spell it regardless of which lint root reached the file."""
    p = pathlib.Path(path)
    if p.suffix != ".py":
        return p.name or "module"
    parts = [] if p.stem == "__init__" else [p.stem]
    parent = p.parent
    while parent.name and (parent / "__init__.py").exists():
        parts.insert(0, parent.name)
        parent = parent.parent
    return ".".join(parts) or p.stem


@dataclasses.dataclass
class ClassSymbols:
    """One class's concurrency-relevant surface."""

    name: str
    module: str
    node: object  # the ast.ClassDef
    lock_attrs: set = dataclasses.field(default_factory=set)
    guarded: dict = dataclasses.field(default_factory=dict)  # attr -> lock
    assigned_attrs: set = dataclasses.field(default_factory=set)
    spawns_thread: bool = False
    thread_targets: set = dataclasses.field(default_factory=set)
    methods: dict = dataclasses.field(default_factory=dict)  # name -> node
    # Lifecycle protocol (jaxlint v4): `# protocol:` comment on the
    # class header. `protocol_pairs` is [(acquire, release), ...];
    # `protocol_terminal` is the set of terminal method names.
    protocol_pairs: list = dataclasses.field(default_factory=list)
    protocol_terminal: set = dataclasses.field(default_factory=set)

    def has_protocols(self) -> bool:
        return bool(self.protocol_pairs or self.protocol_terminal)

    def protocol_methods(self) -> set:
        """Every method name that participates in a declared protocol."""
        out = set(self.protocol_terminal)
        for a, b in self.protocol_pairs:
            out.add(a)
            out.add(b)
        return out

    def lock_ids(self):
        return {f"{self.module}.{self.name}.{a}" for a in sorted(self.lock_attrs)}


@dataclasses.dataclass
class ModuleSymbols:
    """Everything pass 2 needs to know about one module."""

    name: str
    path: str
    str_consts: dict = dataclasses.field(default_factory=dict)
    meshes: dict = dataclasses.field(default_factory=dict)  # var -> (axes, known)
    mesh_union: tuple = (frozenset(), False)  # (axes, known) over every Mesh call
    has_mesh: bool = False
    imports: dict = dataclasses.field(default_factory=dict)  # name -> (module, symbol|None)
    module_locks: set = dataclasses.field(default_factory=set)
    classes: dict = dataclasses.field(default_factory=dict)  # name -> ClassSymbols
    functions: dict = dataclasses.field(default_factory=dict)  # name -> node
    func_locks: dict = dataclasses.field(default_factory=dict)  # qualname -> set[id]
    lock_edges: list = dataclasses.field(default_factory=list)  # (outer, inner, line, col)
    lock_calls: list = dataclasses.field(default_factory=list)  # (held, callee, line, col)
    contracts: dict = dataclasses.field(default_factory=dict)  # qualname -> contract
    schemas: dict = dataclasses.field(default_factory=dict)  # qualname -> (name, version)


# --- collection helpers ----------------------------------------------------


def _module_str_constants(tree) -> dict:
    """Module-level `NAME = "literal"` bindings — how mesh axis names
    are spelled in this repo (e.g. `DATA_AXIS = "data"`)."""
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = node.value.value
    return out


def _mesh_axes_from_call(call: ast.Call, str_consts) -> tuple:
    """(axis-name set, known) for one `Mesh(...)` call. Axis names come
    from the second positional argument or `axis_names=`; string
    constants and module-level string bindings resolve, anything else
    makes the set unknown (known=False) so the axis-name check stays
    quiet rather than guessing."""
    spec = call.args[1] if len(call.args) >= 2 else None
    for kw in call.keywords:
        if kw.arg == "axis_names":
            spec = kw.value
    if spec is None:
        return frozenset(), False
    axes = set()
    elts = spec.elts if isinstance(spec, (ast.Tuple, ast.List)) else [spec]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            axes.add(e.value)
        elif isinstance(e, ast.Name) and e.id in str_consts:
            axes.add(str_consts[e.id])
        else:
            return frozenset(), False
    return frozenset(axes), True


def _collect_meshes(tree, str_consts):
    """(per-name meshes, (union axes, union known), has_mesh) over every
    `Mesh(...)` call — named assignments feed cross-module resolution,
    the union preserves the v1 whole-module fallback semantics."""
    meshes = {}
    union: set = set()
    union_known = True
    has_mesh = False

    def is_mesh_call(node):
        if not isinstance(node, ast.Call):
            return False
        fname = dotted(node.func)
        return fname is not None and fname.split(".")[-1] == "Mesh"

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and is_mesh_call(node.value):
            axes, known = _mesh_axes_from_call(node.value, str_consts)
            for tgt in node.targets:
                name = dotted(tgt)
                if name:
                    meshes[name] = (axes, known)
        if is_mesh_call(node):
            has_mesh = True
            axes, known = _mesh_axes_from_call(node, str_consts)
            if known:
                union |= set(axes)
            else:
                union_known = False
    if not has_mesh:
        return meshes, (frozenset(), False), False
    return meshes, (frozenset(union), union_known), True


def _collect_imports(tree, mod_name: str) -> dict:
    """local binding -> (source module, symbol|None). `import x.y as z`
    binds z to the module; `from x import y` binds y to x's symbol y
    (which may itself be a submodule — resolution tries both)."""
    imports = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                imports[alias.asname or alias.name] = (alias.name, None)
        elif isinstance(node, ast.ImportFrom):
            module = node.module or ""
            if node.level:
                base = mod_name.split(".")
                base = base[: len(base) - node.level]
                module = ".".join(base + ([module] if module else []))
            for alias in node.names:
                imports[alias.asname or alias.name] = (module, alias.name)
    return imports


def _self_attr_writes(stmt):
    """(attr, node) for every `self.X = / self.X[...] = / self.X += ...`
    store in one statement — tuple targets unpacked, subscript chains
    peeled back to the attribute they mutate."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out = []
    while targets:
        tgt = targets.pop()
        if isinstance(tgt, (ast.Tuple, ast.List)):
            targets.extend(tgt.elts)
            continue
        node = tgt
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            if node.value.id == "self":
                out.append((node.attr, tgt))
    return out


_COMPOUND_BODY_FIELDS = ("body", "orelse", "finalbody")


def scan_function(fn_node, resolve_item, held0=()):
    """Walk one function's statements tracking the held-lock stack.

    `resolve_item(expr)` maps a with-item expression to a lock token
    (any hashable) or None; `held0` seeds the stack (the `_locked`
    method convention). Returns `(acquired, edges, stmts)`:

    - acquired: every lock token acquired anywhere in the function
    - edges: (outer, inner, node) for each acquisition made while
      another lock was already held — the lock-order graph's raw edges
    - stmts: (stmt, held_tuple) for every statement, nested defs
      excluded (their bodies run later; a surrounding `with` does not
      guard them)
    """
    acquired = set()
    edges = []
    stmts = []

    def walk(body, held):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                stmts.append((stmt, tuple(held)))
                inner = list(held)
                for item in stmt.items:
                    lock_id = resolve_item(item.context_expr)
                    if lock_id is not None:
                        for outer in inner:
                            edges.append((outer, lock_id, item.context_expr))
                        inner.append(lock_id)
                        acquired.add(lock_id)
                walk(stmt.body, inner)
            else:
                stmts.append((stmt, tuple(held)))
                for field in _COMPOUND_BODY_FIELDS:
                    child = getattr(stmt, field, None)
                    if child:
                        walk(child, held)
                for handler in getattr(stmt, "handlers", []):
                    walk(handler.body, held)

    walk(fn_node.body, list(held0))
    return acquired, edges, stmts


def make_lock_resolver(symbols: ModuleSymbols, cls: ClassSymbols | None):
    """A resolve_item for `scan_function` mapping with-item expressions
    to project-global lock ids: `self.X` through the class's lock
    attrs, bare/dotted names through module locks and the import
    table (resolution is name-based — `from locks import A` and the
    defining module's own `with A:` land on the same id)."""

    def resolve(expr):
        name = dotted(expr)
        if name is None:
            return None
        parts = name.split(".")
        if parts[0] == "self":
            if cls is not None and len(parts) == 2 and parts[1] in cls.lock_attrs:
                return f"{symbols.name}.{cls.name}.{parts[1]}"
            return None
        if len(parts) == 1 and name in symbols.module_locks:
            return f"{symbols.name}.{name}"
        # Imported lock: longest dotted prefix bound by an import.
        for i in range(len(parts), 0, -1):
            head = ".".join(parts[:i])
            if head in symbols.imports:
                src, symbol = symbols.imports[head]
                rest = parts[i:]
                if symbol is not None:
                    rest = [symbol] + rest
                if len(rest) == 1:
                    return f"{src}.{rest[0]}"
                if len(rest) > 1:
                    return f"{src}.{'.'.join(rest)}"
        return None

    return resolve


def _callee_key(call: ast.Call, cls: ClassSymbols | None):
    """('self', class, method) for same-class calls, ('name', dotted)
    for plain/imported callables, None when unresolvable."""
    fname = dotted(call.func)
    if fname is None:
        return None
    parts = fname.split(".")
    if parts[0] == "self":
        if cls is not None and len(parts) == 2:
            return ("self", cls.name, parts[1])
        return None
    return ("name", fname)


def _stmt_exprs(stmt):
    """The statement's own expression roots (headers, values, targets),
    nested statement lists excluded."""
    roots = []
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.AST):
            roots.append(value)
        elif isinstance(value, list):
            roots.extend(v for v in value if isinstance(v, ast.AST))
    for root in roots:
        yield from ast.walk(root)  # walk includes the root itself


# --- per-module build ------------------------------------------------------


def module_symbols(path: str, tree, comments: dict) -> ModuleSymbols:
    """Build one module's symbols. `comments` maps line number -> the
    comment text on that line (jaxlint's tokenize pass supplies it; the
    `guarded_by:` convention is read from there)."""
    name = module_name_for(path)
    sym = ModuleSymbols(name=name, path=path)
    sym.str_consts = _module_str_constants(tree)
    sym.meshes, sym.mesh_union, sym.has_mesh = _collect_meshes(tree, sym.str_consts)
    sym.imports = _collect_imports(tree, name)

    # Module-level locks.
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fname = dotted(node.value.func)
            if fname and fname.split(".")[-1] in LOCK_FACTORY_TAILS:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        sym.module_locks.add(tgt.id)

    # Classes: locks, guarded_by annotations, thread spawning.
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        cls = ClassSymbols(name=node.name, module=name, node=node)
        # `# protocol:` sits on the class header (same line as the
        # `class` keyword, or a continuation line before the body).
        first_body_line = node.body[0].lineno if node.body else node.lineno
        for ln in range(node.lineno, max(first_body_line, node.lineno + 1)):
            pairs, terminal = parse_protocols(comments.get(ln, ""))
            cls.protocol_pairs.extend(pairs)
            cls.protocol_terminal |= terminal
            schema = parse_schema(comments.get(ln, ""))
            if schema is not None:
                sym.schemas[node.name] = schema
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fname = dotted(sub.func)
                tail = fname.split(".")[-1] if fname else ""
                if tail == "Thread":
                    cls.spawns_thread = True
                    for kw in sub.keywords:
                        if kw.arg == "target":
                            tname = dotted(kw.value)
                            if tname and tname.startswith("self."):
                                cls.thread_targets.add(tname.split(".", 1)[1])
            if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for attr, _tgt in _self_attr_writes(sub):
                    cls.assigned_attrs.add(attr)
                    comment = comments.get(sub.lineno, "")
                    match = GUARDED_BY_RE.search(comment)
                    if match:
                        cls.guarded[attr] = match.group(1)
                if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                    fname = dotted(sub.value.func)
                    if fname and fname.split(".")[-1] in LOCK_FACTORY_TAILS:
                        for attr, _tgt in _self_attr_writes(sub):
                            cls.lock_attrs.add(attr)
        # A guard annotation names a lock even if its constructor is
        # spelled indirectly; trust the contract.
        cls.lock_attrs |= set(cls.guarded.values())
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls.methods[item.name] = item
        sym.classes[node.name] = cls

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sym.functions[node.name] = node

    # Lock-order graph: direct acquisitions + calls made while holding.
    def scan_scope(fn_node, cls, qualname):
        # `# deterministic` / `# pure-render(view)` sits on the def
        # header (same line as the `def` keyword, or a continuation
        # line of a wrapped signature before the body) — the same
        # placement rule as the class-header `# protocol:` scan.
        first_body_line = fn_node.body[0].lineno if fn_node.body else fn_node.lineno
        for ln in range(fn_node.lineno, max(first_body_line, fn_node.lineno + 1)):
            contract = parse_contract(comments.get(ln, ""))
            if contract is not None:
                sym.contracts[qualname] = contract
            schema = parse_schema(comments.get(ln, ""))
            if schema is not None:
                sym.schemas[qualname] = schema
        resolver = make_lock_resolver(sym, cls)
        held0 = ()
        if cls is not None and fn_node.name.endswith(LOCKED_SUFFIX):
            held0 = tuple(sorted(cls.lock_ids()))
        acquired, edges, stmts = scan_function(fn_node, resolver, held0)
        sym.func_locks[qualname] = acquired
        for outer, inner, site in edges:
            sym.lock_edges.append((outer, inner, site.lineno, site.col_offset))
        for stmt, held in stmts:
            if not held:
                continue
            for expr in _stmt_exprs(stmt):
                if isinstance(expr, ast.Call):
                    key = _callee_key(expr, cls)
                    if key is not None:
                        sym.lock_calls.append(
                            (tuple(held), key, expr.lineno, expr.col_offset)
                        )

    for fname, fn_node in sym.functions.items():
        scan_scope(fn_node, None, fname)
    for cls in sym.classes.values():
        for mname, mnode in cls.methods.items():
            scan_scope(mnode, cls, f"{cls.name}.{mname}")
    return sym


# --- the project table -----------------------------------------------------


class ProjectTable:
    """Pass-1 output: every linted module's symbols, keyed by dotted
    module name, with suffix-tolerant lookup and one-hop resolution of
    imported meshes and callables."""

    def __init__(self, modules):
        self.modules = {}
        for m in modules:
            self.modules[m.name] = m
        self._edges = None

    def module(self, name: str) -> ModuleSymbols | None:
        if name in self.modules:
            return self.modules[name]
        for key, mod in self.modules.items():
            if key.endswith("." + name):
                return mod
        for key, mod in self.modules.items():
            if name.endswith("." + key):
                return mod
        return None

    def resolve_mesh(self, mod: ModuleSymbols, dotted_name: str):
        """(axes, known) for a mesh referenced by name in `mod` —
        locally assigned, or reached through `from x import mesh` /
        `import x as alias; alias.mesh` chains. None = not a mesh the
        table can see."""
        if dotted_name in mod.meshes:
            return mod.meshes[dotted_name]
        parts = dotted_name.split(".")
        for i in range(len(parts), 0, -1):
            head = ".".join(parts[:i])
            if head not in mod.imports:
                continue
            src_name, symbol = mod.imports[head]
            rest = parts[i:]
            if symbol is not None:
                src = self.module(src_name)
                if src is not None and not rest and symbol in src.meshes:
                    return src.meshes[symbol]
                # `from pkg import submodule` then `submodule.mesh`:
                sub = self.module(f"{src_name}.{symbol}")
                if sub is not None and rest and rest[0] in sub.meshes:
                    return sub.meshes[rest[0]]
            else:
                src = self.module(src_name)
                if src is not None and rest and rest[0] in src.meshes:
                    return src.meshes[rest[0]]
                if rest:
                    sub = self.module(f"{src_name}.{rest[0]}")
                    if sub is not None and len(rest) > 1 and rest[1] in sub.meshes:
                        return sub.meshes[rest[1]]
        return None

    def callee_locks(self, mod: ModuleSymbols, callee) -> set:
        """Locks a called function/method acquires directly — one hop,
        resolved through the table for imported callables."""
        kind = callee[0]
        if kind == "self":
            _kind, cls_name, meth = callee
            return mod.func_locks.get(f"{cls_name}.{meth}", set())
        _kind, fname = callee
        if fname in mod.func_locks:
            return mod.func_locks[fname]
        parts = fname.split(".")
        for i in range(len(parts), 0, -1):
            head = ".".join(parts[:i])
            if head not in mod.imports:
                continue
            src_name, symbol = mod.imports[head]
            rest = parts[i:]
            if symbol is not None:
                rest = [symbol] + rest
            src = self.module(src_name)
            if src is not None and len(rest) == 1:
                return src.func_locks.get(rest[0], set())
            if src is not None and len(rest) == 2:
                return src.func_locks.get(f"{rest[0]}.{rest[1]}", set())
        return set()

    def all_lock_edges(self):
        """The project-wide lock-order graph: every direct nesting edge
        plus call-through edges (a lock held across a call to code that
        acquires another lock), as (outer, inner, module, line, col)."""
        if self._edges is not None:
            return self._edges
        edges = []
        for mod in self.modules.values():
            for outer, inner, line, col in mod.lock_edges:
                edges.append((outer, inner, mod.name, line, col))
            for held, callee, line, col in mod.lock_calls:
                for inner in sorted(self.callee_locks(mod, callee)):
                    for outer in held:
                        edges.append((outer, inner, mod.name, line, col))
        self._edges = edges
        return edges
