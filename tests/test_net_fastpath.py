"""The fast wire path (PR 16): watermark-keyed byte cache, batched
/query endpoint, and the selectors event-loop front end.

The contract under test, end to end over real localhost HTTP:

- cached responses are BYTE-identical to a fresh render at the same
  watermark (the head-splice property: only the per-request trace id
  differs, spliced in after the cached head);
- a watermark advance guarantees invalidation — the next read serves
  the new view, never yesterday's bytes (the audit's
  cache-not-invalidated-on-watermark-advance mutant dies here);
- the stale flag passes through during restore, uncached in both
  directions;
- one batched POST /query answers every lookup from ONE view (the
  audit's batch-endpoint-splits-views-across-one-request mutant);
- the event loop is the DEFAULT read front end, observable via
  /healthz and its named thread (the audit's
  event-loop-read-falls-back-to-blocking-silently mutant);
- 8 reader threads hammering the cache while ingest advances the view
  never see a torn response or a watermark regression.
"""

import json
import socket
import threading

import numpy as np
import pytest

from arena.net import fastpath, protocol
from arena.net.fastpath import (
    ResponseCache,
    cache_key,
    complete_response,
    render_head,
)
from arena.net.protocol import (
    MAX_BATCH_QUERIES,
    ProtocolError,
    WireClient,
    make_response,
    parse_query_body,
)
from arena.net.server import ArenaHTTPServer
from arena.obs import NULL, Observability
from arena.serving import ArenaServer

PLAYERS = 24


def _ingest(srv, n, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.integers(0, PLAYERS, n).astype(np.int32)
    b = (a + 1 + rng.integers(0, PLAYERS - 1, n)).astype(np.int32) % PLAYERS
    srv.engine.ingest(a, b)


@pytest.fixture(scope="module")
def wire():
    """One event-loop wire server over a max_staleness=0 ArenaServer
    (every ingest advance forces a refresh on the next read)."""
    obs = Observability()
    srv = ArenaServer(num_players=PLAYERS, max_staleness_matches=0, obs=obs)
    _ingest(srv, 300)
    server = ArenaHTTPServer(srv).start()
    client = WireClient(server.host, server.port)
    yield server, client
    client.close()
    server.close()
    srv.close()


# --- the byte-splice property (pure) ----------------------------------------


def test_head_splice_is_byte_identical_to_a_fresh_envelope_dump():
    """The property the whole cache stands on: a cached head completed
    with a request's trace id equals `json.dumps(make_response(...))`
    for that trace id, byte for byte — for any payload, including ones
    carrying their own (stripped) watermark/trace pair."""
    payloads = [
        {"leaderboard": [{"player": 3, "rating": 1501.25, "lo": None}]},
        {"x": 1, "watermark": 999, "trace_id": 999},
        {"stale": False, "nested": {"a": [1, 2, 3]}, "f": 0.1 + 0.2},
    ]
    for payload in payloads:
        for trace_id in (1, 7, 123456789):
            head = render_head(payload, watermark=42)
            fresh = json.dumps(
                make_response(payload, watermark=42, trace_id=trace_id)
            ).encode("utf-8")
            assert complete_response(head, trace_id) == fresh


def test_cached_bytes_equal_fresh_render_at_same_watermark(wire):
    """Same watermark, same params: the cached response and a fresh
    render agree on every byte except the trace id — asserted through
    the same consistency gate the frontend bench hard-fails on."""
    server, client = wire
    srv = server.server
    _status, first = client.get("/leaderboard?offset=0&limit=6")
    hits_before = srv.obs.registry.counter_sum("arena_wire_cache_hits_total")
    _status, second = client.get("/leaderboard?offset=0&limit=6")
    hits_after = srv.obs.registry.counter_sum("arena_wire_cache_hits_total")
    assert hits_after > hits_before, "second read should be a cache hit"
    assert second["trace_id"] != first["trace_id"]
    assert {k: v for k, v in second.items() if k != "trace_id"} == {
        k: v for k, v in first.items() if k != "trace_id"
    }
    checked, mismatches = server.verify_cache_consistency()
    assert checked >= 1
    assert mismatches == []


def test_cache_invalidates_when_watermark_advances(wire):
    """Named kill for the audit's
    cache-not-invalidated-on-watermark-advance mutant (a `get` that
    ignores the view generation): after the watermark advances, the
    same read serves the NEW view — watermark, ingest count, and rows
    all fresh, never yesterday's bytes. Uses /player (not a
    prerendered page, so a stale-serving `get` cannot be rescued by
    the refresh-time prerender refill)."""
    server, client = wire
    srv = server.server
    status, before = client.get("/player/3")
    assert status == 200
    assert before["watermark"] == srv.engine.matches_applied
    _ingest(srv, 40, seed=99)  # advances the watermark; staleness bound 0
    status, after = client.get("/player/3")
    assert status == 200
    assert after["watermark"] == srv.engine.matches_applied
    assert after["watermark"] > before["watermark"]
    assert after["matches_ingested"] > before["matches_ingested"]
    assert after["view_seq"] > before["view_seq"]
    # And the fresh bytes are themselves cached + consistent.
    checked, mismatches = server.verify_cache_consistency()
    assert checked >= 1 and mismatches == []


def test_stale_flag_passes_through_during_restore(wire):
    """While a restore is in flight the serving tier answers from the
    last complete view with stale=true — the cache must not launder
    that into a fresh-looking stale=false hit, nor cache the stale
    render for later."""
    server, client = wire
    srv = server.server
    _status, fresh = client.get("/h2h?a=1&b=2")
    assert fresh["stale"] is False
    srv._restoring = True
    try:
        _ingest(srv, 10, seed=7)
        status, stale = client.get("/h2h?a=1&b=2")
        assert status == 200
        assert stale["stale"] is True
        assert stale["staleness"] > 0
    finally:
        srv._restoring = False
    # Back to normal: the stale render was NOT cached — the next read
    # reflects the post-restore view, stale=false again.
    _status, after = client.get("/h2h?a=1&b=2")
    assert after["stale"] is False
    assert after["watermark"] == srv.engine.matches_applied


def test_prerendered_hot_pages_hit_without_a_prior_read(wire):
    """Satellite (c): refresh_view prerenders the hot leaderboard
    pages, so the FIRST wire read of a fresh view's top page is
    already a cache hit."""
    server, client = wire
    srv = server.server
    reg = srv.obs.registry
    _ingest(srv, 10, seed=11)
    srv.refresh_view()  # fires the prerender listener
    pre = reg.counter_sum("arena_wire_cache_prerenders_total")
    assert pre >= len(server._prerender_pages)
    hits_before = reg.counter_sum("arena_wire_cache_hits_total")
    offset, limit = server._prerender_pages[0]
    status, page = client.get(f"/leaderboard?offset={offset}&limit={limit}")
    assert status == 200
    assert reg.counter_sum("arena_wire_cache_hits_total") > hits_before
    ratings = [row["rating"] for row in page["leaderboard"]]
    assert ratings == sorted(ratings, reverse=True)


# --- the batched /query endpoint --------------------------------------------


def test_batch_query_answers_every_part_from_one_view():
    """Named kill for the audit's
    batch-endpoint-splits-views-across-one-request mutant (a per-spec
    `_serve_view()`): with ingest advancing after every refresh and a
    zero staleness bound, a per-spec view choice would hand each spec
    a DIFFERENT view_seq — the batch contract is one view, one
    watermark, one seq across all results."""
    srv = ArenaServer(num_players=PLAYERS, max_staleness_matches=0, obs=NULL)
    try:
        _ingest(srv, 100)
        real_refresh = srv.refresh_view

        def refresh_then_advance():
            view = real_refresh()
            # New matches land right after every refresh: any SECOND
            # _serve_view() in the same batch sees staleness > 0 and
            # refreshes again, splitting the batch across views.
            srv.engine.ingest(
                np.array([0], np.int32), np.array([1], np.int32)
            )
            return view

        srv.refresh_view = refresh_then_advance
        out = srv.query_batch([
            {"leaderboard": (0, 5)},
            {"players": [1, 2, 3]},
            {"pairs": [(0, 1), (2, 3)]},
        ])
        seqs = {r["view_seq"] for r in out["results"]}
        assert len(seqs) == 1, f"batch split across views: {seqs}"
        assert {r["watermark"] for r in out["results"]} == {out["watermark"]}
        assert out["queries"] == 3
        assert out["view_seq"] in seqs
        assert "leaderboard" in out["results"][0]
        assert "players" in out["results"][1]
        assert "pairs" in out["results"][2]
    finally:
        del srv.refresh_view
        srv.close()


def test_batch_query_over_the_wire_matches_singles(wire):
    """POST /query returns the same rows the single-lookup GETs serve,
    index-aligned with the request, wearing the standard envelope."""
    server, client = wire
    status, batch = client.batch_query([
        {"leaderboard": [0, 5]},
        {"players": [4]},
        {"pairs": [[2, 5]]},
    ])
    assert status == 200
    assert batch["queries"] == 3 and len(batch["results"]) == 3
    assert "watermark" in batch and "trace_id" in batch
    _status, lb = client.get("/leaderboard?offset=0&limit=5")
    _status, player = client.get("/player/4")
    _status, h2h = client.get("/h2h?a=2&b=5")
    assert batch["results"][0]["leaderboard"] == lb["leaderboard"]
    assert batch["results"][1]["players"] == player["players"]
    assert batch["results"][2]["pairs"] == h2h["pairs"]
    # Bad ids reject the whole batch — nothing partially served.
    status, err = client.batch_query([{"players": [PLAYERS + 50]}])
    assert status == 400 and "error" in err


def test_parse_query_body_validates_shape():
    specs = parse_query_body(json.dumps({
        "queries": [
            {"leaderboard": [0, 10]},
            {"players": [1, 2], "pairs": [[3, 4]]},
        ],
    }).encode("utf-8"))
    assert specs == [
        {"leaderboard": (0, 10)},
        {"players": [1, 2], "pairs": [(3, 4)]},
    ]
    for raw in [
        b"not json",
        b"[]",
        b"{}",
        b'{"queries": []}',
        b'{"queries": ["x"]}',
        b'{"queries": [{}]}',
        b'{"queries": [{"nope": 1}]}',
        b'{"queries": [{"leaderboard": [0]}]}',
        b'{"queries": [{"leaderboard": [0, true]}]}',
        b'{"queries": [{"players": [1.5]}]}',
        b'{"queries": [{"pairs": [[1]]}]}',
        b'{"queries": [{"pairs": [1, 2]}]}',
    ]:
        with pytest.raises(ProtocolError) as exc:
            parse_query_body(raw)
        assert exc.value.status == 400, raw
    over = {"queries": [{"players": [0]}] * (MAX_BATCH_QUERIES + 1)}
    with pytest.raises(ProtocolError) as exc:
        parse_query_body(json.dumps(over).encode("utf-8"))
    assert exc.value.status == 400


def test_wire_client_reuses_one_connection_across_batched_posts(wire):
    """Satellite (b): batched POSTs ride ONE persistent connection —
    connections_opened stays at 1 across a mixed GET/POST workload."""
    server, _client = wire
    fresh = WireClient(server.host, server.port)
    try:
        for _ in range(5):
            status, resp = fresh.batch_query([{"leaderboard": [0, 3]}])
            assert status == 200 and resp["queries"] == 1
            status, _h = fresh.get("/healthz")
            assert status == 200
        assert fresh.connections_opened == 1
    finally:
        fresh.close()


# --- the event-loop front end -----------------------------------------------


def test_default_front_end_is_the_event_loop(wire):
    """Named kill for the audit's
    event-loop-read-falls-back-to-blocking-silently mutant: the
    selectors loop is the DEFAULT front end, and the fallback is
    observable — /healthz reports front_end, and the loop's named
    thread is live. A silent fallback to thread-per-connection would
    pass every functional test while quietly reverting the perf
    tentpole; this test makes it loud."""
    server, client = wire
    assert server.front_end == "eventloop"
    status, health = client.get("/healthz")
    assert status == 200
    assert health["front_end"] == "eventloop"
    names = [t.name for t in threading.enumerate()]
    assert fastpath.LOOP_THREAD_NAME in names
    assert any(n.startswith(fastpath.SUBMIT_WORKER_PREFIX) for n in names)


def test_threaded_fallback_serves_the_same_protocol():
    """fastpath_reads=False keeps the legacy ThreadingHTTPServer front
    end on the SAME request core: every endpoint (including /query and
    the cache) behaves identically, and /healthz says so."""
    srv = ArenaServer(num_players=PLAYERS, max_staleness_matches=0, obs=NULL)
    try:
        _ingest(srv, 60)
        with ArenaHTTPServer(srv, fastpath_reads=False) as server:
            assert server.front_end == "threaded"
            client = WireClient(server.host, server.port)
            status, health = client.get("/healthz")
            assert status == 200 and health["front_end"] == "threaded"
            status, lb = client.get("/leaderboard?offset=0&limit=5")
            assert status == 200
            status, again = client.get("/leaderboard?offset=0&limit=5")
            assert {k: v for k, v in again.items() if k != "trace_id"} == {
                k: v for k, v in lb.items() if k != "trace_id"
            }
            status, batch = client.batch_query([{"players": [1]}])
            assert status == 200
            assert batch["results"][0]["players"][0]["player"] == 1
            checked, mismatches = server.verify_cache_consistency()
            assert checked >= 1 and mismatches == []
            client.close()
    finally:
        srv.close()


def test_event_loop_answers_malformed_framing_then_closes(wire):
    """Garbage on the socket gets ONE structured error response and a
    closed connection — never a hung loop or an unbounded buffer."""
    server, _client = wire
    for raw, want in [
        (b"GARBAGE\r\n\r\n", b"400"),
        (b"GET /healthz HTTP/9.9\r\n\r\n", b"505"),
        (b"POST /query HTTP/1.1\r\nContent-Length: 999999999999\r\n\r\n",
         b"413"),
        (b"GET /healthz HTTP/1.1\r\nContent-Length: nope\r\n\r\n", b"400"),
    ]:
        with socket.create_connection(
            (server.host, server.port), timeout=10
        ) as sock:
            sock.sendall(raw)
            data = b""
            while b"\r\n" not in data:
                chunk = sock.recv(65536)
                if not chunk:
                    break
                data += chunk
            status_line = data.split(b"\r\n", 1)[0]
            assert want in status_line, (raw, status_line)
            # The connection drains to EOF: closed after one answer.
            sock.settimeout(10)
            while True:
                tail = sock.recv(65536)
                if not tail:
                    break
    # The loop survived all of it.
    status, _h = _client.get("/healthz")
    assert status == 200


def test_event_loop_serves_pipelined_requests_in_order(wire):
    """Two requests in one TCP segment come back as two well-formed
    responses, in order (the _advance loop drains the input buffer)."""
    server, _client = wire
    raw = (
        b"GET /healthz HTTP/1.1\r\n\r\n"
        b"GET /healthz HTTP/1.1\r\nConnection: close\r\n\r\n"
    )
    with socket.create_connection((server.host, server.port), timeout=10) as sock:
        sock.sendall(raw)
        data = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            data += chunk
    assert data.count(b"HTTP/1.1 200 OK") == 2
    assert data.count(b'"status": "ok"') == 2


# --- the cache object itself ------------------------------------------------


def test_response_cache_eviction_prefers_dead_generations():
    cache = ResponseCache(NULL, capacity=3)
    cache.put(("a", ()), 1, b"a1")
    cache.put(("b", ()), 1, b"b1")
    cache.put(("c", ()), 2, b"c2")  # generation advances to 2
    assert cache.get(("c", ()), 2) == b"c2"
    assert cache.get(("a", ()), 2) is None  # dead generation: no hit
    # At capacity: the next put drops the dead gen-1 entries first.
    cache.put(("d", ()), 2, b"d2")
    assert cache.size() == 2  # a1 + b1 evicted, c2 + d2 live
    assert cache.get(("d", ()), 2) == b"d2"
    # All-live eviction still bounds the table.
    cache.put(("e", ()), 2, b"e2")
    cache.put(("f", ()), 2, b"f2")
    assert cache.size() == 3
    cache.close()


def test_response_cache_drops_stale_puts_and_closes_terminally():
    cache = ResponseCache(NULL, capacity=4)
    cache.put(("k", ()), 5, b"new")
    cache.put(("k", ()), 3, b"old")  # a slow render from a dead view
    assert cache.get(("k", ()), 5) == b"new"
    cache.close()
    # Deliberate post-close probes: close() is terminal and must stay
    # safe (refuse fills, answer None), which only a post-close call
    # can assert.
    assert cache.size() == 0  # jaxlint: disable=use-after-close
    cache.put(("k", ()), 6, b"refused")  # jaxlint: disable=use-after-close
    assert cache.size() == 0  # jaxlint: disable=use-after-close
    assert cache.get(("k", ()), 6) is None  # jaxlint: disable=use-after-close
    with pytest.raises(ValueError):
        ResponseCache(NULL, capacity=0)


def test_cache_key_canonicalizes_param_order():
    assert cache_key("leaderboard", {"offset": 0, "limit": 10}) == cache_key(
        "leaderboard", {"limit": 10, "offset": 0}
    )


# --- concurrency: 8 readers vs live ingest ----------------------------------


def test_eight_readers_hammer_the_cache_while_ingest_advances(wire):
    """Satellite: 8 reader threads over real HTTP against a zero
    staleness bound while the main thread ingests — every response
    well-formed, per-reader watermarks monotone (a cache serving dead
    bytes regresses them), and the consistency gate clean at the end."""
    server, _client = wire
    srv = server.server
    stop = threading.Event()
    errors = []
    rounds = [0] * 8

    def reader(rid):
        client = WireClient(server.host, server.port)
        last = -1
        try:
            while not stop.is_set():
                for path in (
                    "/leaderboard?offset=0&limit=10",
                    f"/player/{rid}",
                    f"/h2h?a={rid}&b={(rid + 1) % PLAYERS}",
                ):
                    status, resp = client.get(path)
                    if status != 200:
                        errors.append((rid, path, status, resp))
                        return
                    if resp["watermark"] < last:
                        errors.append((rid, "watermark regressed",
                                       resp["watermark"], last))
                        return
                    last = resp["watermark"]
                rounds[rid] += 1
        except Exception as exc:  # noqa: BLE001 — surfaced via errors
            errors.append((rid, "exception", repr(exc)))
        finally:
            client.close()

    threads = [
        threading.Thread(target=reader, args=(i,), daemon=True)
        for i in range(8)
    ]
    for t in threads:
        t.start()
    for i in range(12):
        _ingest(srv, 20, seed=1000 + i)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    assert not errors, errors[:5]
    assert all(r > 0 for r in rounds), rounds
    checked, mismatches = server.verify_cache_consistency()
    assert mismatches == []
    reg = srv.obs.registry
    assert reg.counter_sum("arena_wire_cache_hits_total") > 0
    assert reg.counter_sum("arena_wire_cache_misses_total") > 0
