"""jaxlint corpus: replicated state written outside the apply closure.

`apply` is the `# deterministic; mutates:` apply root: its declared
write set (`ratings`, `matches_applied`) IS the replicated state a
log-replaying replica reconstructs, and `_bump` is inside the apply
call closure, so its writes replay fine. `recalibrate` is NOT in that
closure — an operator convenience that rescales ratings in place. A
replica replaying the match log never executes it, so the moment it
runs, primary and replica disagree forever after.
Rule: replication-boundary-write.
"""


class ReplicaRatings:
    def __init__(self):
        self.ratings = {}
        self.matches_applied = 0

    def apply(self, batch):  # deterministic; mutates: ratings, matches_applied
        for player, delta in batch:
            self._bump(player, delta)

    def _bump(self, player, delta):
        self.ratings[player] = self.ratings.get(player, 0.0) + delta
        self.matches_applied += 1

    def recalibrate(self, scale):
        for player in list(self.ratings):
            self.ratings[player] = self.ratings[player] * scale
