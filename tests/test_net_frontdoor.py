"""Front-door contracts: the deterministic total order, bounded-
degradation shedding, and crash-restart with spilled per-producer
queues (arena/net/frontdoor.py).

The property this file exists to police is ISSUE 9's: under N
concurrent producers the applied stream is ONE well-defined sequence
order, and replaying that order through synchronous single-producer
`ingest()` lands on BIT-EXACT the same ratings — including under
shedding (the coalesced summary is applied deterministically at the
shed batches' position) and across a crash-restart that spills the
per-producer queues. The mutation audit carries the
sequence-order-ignored-at-merge and summary-update-omitted mutants;
`test_merge_applies_sequence_order_not_arrival_order` and
`test_shed_batches_coalesce_into_summary_update` are their named
kills.
"""

import threading

import numpy as np
import pytest

from arena.engine import ArenaEngine
from arena.net import FrontDoor, FrontDoorError, POLICY_STALENESS
from arena.obs import Observability

PLAYERS = 32


def make_batch(rng, n=40):
    a = rng.integers(0, PLAYERS, n).astype(np.int32)
    b = ((a + 1 + rng.integers(0, PLAYERS - 1, n)) % PLAYERS).astype(np.int32)
    return a, b


def replay_sync(applied_log, num_players=PLAYERS):
    """The equivalence anchor: the applied log through a fresh sync
    single-producer engine, in order."""
    eng = ArenaEngine(num_players)
    for _kind, w, l in applied_log:
        eng.ingest(w, l)
    return np.asarray(eng.ratings)


def test_merge_applies_sequence_order_not_arrival_order():
    """Admission order (sequence numbers) is the total order — NOT the
    order batch bodies happen to land in the buffer. Two tickets
    delivered in REVERSED order must still apply in sequence order
    (the merge waits for the gap), and the ratings must equal the
    sequence-order sync replay. Elo is order-dependent, so an
    arrival-order merge produces different ratings — the audit's
    sequence-order-ignored-at-merge mutant dies here."""
    rng = np.random.default_rng(7)
    eng = ArenaEngine(PLAYERS)
    fd = FrontDoor(eng, record_applied=True)
    try:
        wa, la = make_batch(rng)
        wb, lb = make_batch(rng)
        ta = fd.admit(wa, la, producer="a")  # seq 0
        tb = fd.admit(wb, lb, producer="b")  # seq 1
        assert (ta.seq, tb.seq) == (0, 1)
        # Bodies land out of order: b first. The merge must NOT apply
        # b — seq 0 has not been delivered yet.
        fd.deliver(tb)
        fd.deliver(ta)
        fd.flush()
    finally:
        fd.close()
    assert [kind for kind, _w, _l in fd.applied_log] == ["batch", "batch"]
    applied_w = [w for _k, w, _l in fd.applied_log]
    assert np.array_equal(applied_w[0], wa), "seq 0 must apply first"
    assert np.array_equal(applied_w[1], wb)
    assert np.array_equal(np.asarray(eng.ratings), replay_sync(fd.applied_log))
    # The orders genuinely differ (the test would be vacuous otherwise).
    eng_arrival = ArenaEngine(PLAYERS)
    eng_arrival.ingest(wb, lb)
    eng_arrival.ingest(wa, la)
    assert not np.array_equal(
        np.asarray(eng.ratings), np.asarray(eng_arrival.ratings)
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_n_producer_random_interleaving_is_bit_exact_to_sync_replay(seed):
    """The headline property, 3 seeds x N=4 producer THREADS: random
    batches, random thread interleavings, one front door — the applied
    log is in admission order and replays bit-exact through a sync
    single-producer engine."""
    rng = np.random.default_rng(seed)
    eng = ArenaEngine(PLAYERS)
    fd = FrontDoor(eng, capacity=64, record_applied=True)
    per_producer = [
        [make_batch(rng, int(rng.integers(8, 64))) for _ in range(6)]
        for _ in range(4)
    ]

    def producer(pid):
        for w, l in per_producer[pid]:
            fd.submit(w, l, producer=f"p{pid}")

    threads = [
        threading.Thread(target=producer, args=(p,)) for p in range(4)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fd.flush()
    finally:
        fd.close()
    total = sum(w.shape[0] for batches in per_producer for w, _l in batches)
    assert eng.matches_ingested == total
    assert len(fd.applied_log) == 24
    assert np.array_equal(np.asarray(eng.ratings), replay_sync(fd.applied_log))


def test_shed_batches_coalesce_into_summary_update():
    """Over-capacity admissions shed the oldest batches — but their
    MATCHES survive as one summary update applied at their slot in the
    total order: nothing is lost, the engine's match count proves it,
    and the replay (summary included) stays bit-exact. The audit's
    summary-update-omitted mutant dies on the count assertion."""
    rng = np.random.default_rng(3)
    obs = Observability()
    eng = ArenaEngine(PLAYERS, obs=obs)
    fd = FrontDoor(
        eng, capacity=3, max_staleness_matches=10_000, record_applied=True
    )
    batches = [make_batch(rng) for _ in range(9)]
    try:
        fd.pause()  # a stalled apply path: admissions pile up
        for i, (w, l) in enumerate(batches):
            fd.submit(w, l, producer=f"p{i % 2}")
        assert fd.shed_batches == 6  # 9 admitted, 3 buffered
        assert fd.dropped_matches == 0  # coalesced, not lost
        fd.resume()
        fd.flush()
    finally:
        fd.close()
    total = sum(w.shape[0] for w, _l in batches)
    # Every admitted match was applied: shed degraded granularity
    # (6 batches became 1 summary), never data.
    assert eng.matches_ingested == total
    assert fd.summaries_applied == 1
    kinds = [kind for kind, _w, _l in fd.applied_log]
    assert kinds == ["summary", "batch", "batch", "batch"]
    # The summary holds the shed batches' matches in sequence order.
    summary_w = fd.applied_log[0][1]
    assert np.array_equal(
        summary_w, np.concatenate([w for w, _l in batches[:6]])
    )
    assert np.array_equal(np.asarray(eng.ratings), replay_sync(fd.applied_log))
    # Shed traces ENDED with the existing marker, and none dangle.
    markers = [s for s in obs.tracer.spans() if s.name == "pipeline.dropped"]
    assert len(markers) == 6
    assert not [
        r for r, reason in obs.tracer.orphans() if reason == "dangling"
    ]
    # The policy-labeled drop counters carry the shed, per producer.
    assert obs.registry.counter_by_label(
        "arena_pipeline_dropped_batches_total", "policy"
    ) == {"coalesce": 6}


def test_staleness_bound_trims_oldest_summary_segments_counted():
    """The summary's backlog is staleness-bounded: beyond
    `max_staleness_matches` its OLDEST segments are dropped for real —
    visible on the existing dropped-matches counter under
    policy="staleness", never silent — and the ratings still replay
    bit-exact over what WAS applied."""
    rng = np.random.default_rng(4)
    obs = Observability()
    eng = ArenaEngine(PLAYERS, obs=obs)
    fd = FrontDoor(
        eng, capacity=2, max_staleness_matches=80, record_applied=True
    )
    batches = [make_batch(rng, 40) for _ in range(10)]
    try:
        fd.pause()
        for w, l in batches:
            fd.submit(w, l, producer="solo")
        # 10 admitted: 2 buffered, 8 shed; the summary holds at most
        # 80 matches = the NEWEST 2 shed batches; 6 x 40 dropped.
        assert fd.shed_batches == 8
        assert fd.dropped_matches == 6 * 40
        assert fd._summary_matches <= 80
        fd.resume()
        fd.flush()
    finally:
        fd.close()
    assert eng.matches_ingested == fd.admitted_matches - fd.dropped_matches
    assert np.array_equal(np.asarray(eng.ratings), replay_sync(fd.applied_log))
    # The trimmed summary kept the NEWEST shed segments (6 and 7), so
    # freshness degraded from the OLD end.
    summary_w = next(w for kind, w, _l in fd.applied_log if kind == "summary")
    assert np.array_equal(
        summary_w, np.concatenate([batches[6][0], batches[7][0]])
    )
    by_policy = obs.registry.counter_by_label(
        "arena_pipeline_dropped_matches_total", "policy"
    )
    assert by_policy.get(POLICY_STALENESS) == 6 * 40
    assert fd.staleness_matches() == 0  # quiescent: fully caught up


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_crash_restart_with_spilled_per_producer_queues_is_bit_exact(
    seed, tmp_path
):
    """Crash mid-stream with batches still queued per producer: the
    spill (summary segments + queued batches in sequence order, each
    under its producer label) persists to disk, a restarted front door
    re-admits it in the same deterministic order, and the final
    ratings are bit-exact to an uninterrupted run over the same
    stream."""
    rng = np.random.default_rng(100 + seed)
    stream = [
        (make_batch(rng, int(rng.integers(16, 48))), f"p{i % 3}")
        for i in range(14)
    ]
    half = 7

    # --- the uninterrupted comparator --------------------------------
    eng_ref = ArenaEngine(PLAYERS)
    fd_ref = FrontDoor(eng_ref, capacity=64)
    for (w, l), producer in stream:
        fd_ref.submit(w, l, producer=producer)
    fd_ref.flush()
    fd_ref.close()

    # --- the crashing run: first half applied, second half queued ----
    eng1 = ArenaEngine(PLAYERS)
    fd1 = FrontDoor(eng1, capacity=64, max_staleness_matches=10_000)
    for (w, l), producer in stream[:half]:
        fd1.submit(w, l, producer=producer)
    fd1.flush()
    fd1.pause()  # the "crash": the apply path stops mid-stream
    # Tighten the buffer so the stalled second half also exercises the
    # coalesce path: part of the spill arrives as summary segments.
    fd1.set_policy(capacity=4)
    for (w, l), producer in stream[half:]:
        fd1.submit(w, l, producer=producer)
    spilled = fd1.close(spill=True)
    assert spilled["queued"] or spilled["summary"]
    # The spill keeps per-producer identity and sequence order.
    seqs = [seq for seq, _p, _w, _l in spilled["queued"]]
    assert seqs == sorted(seqs)
    producers_seen = {p for _s, p, _w, _l in spilled["queued"]} | {
        p for p, _w, _l in spilled["summary"]
    }
    assert len(producers_seen) >= 2
    applied_before_crash = eng1.matches_ingested

    # Persist the spill like a snapshot sidecar and reload it.
    arrays = {}
    summary_meta = []
    for i, (p, w, l) in enumerate(spilled["summary"]):
        arrays[f"sw{i}"], arrays[f"sl{i}"] = w, l
        summary_meta.append(p)
    queued_meta = []
    for i, (seq, p, w, l) in enumerate(spilled["queued"]):
        arrays[f"qw{i}"], arrays[f"ql{i}"] = w, l
        queued_meta.append((seq, p))
    np.savez(tmp_path / "spill.npz", **arrays)
    loaded = np.load(tmp_path / "spill.npz")
    reloaded = {
        "summary": [
            (p, loaded[f"sw{i}"], loaded[f"sl{i}"])
            for i, p in enumerate(summary_meta)
        ],
        "queued": [
            (seq, p, loaded[f"qw{i}"], loaded[f"ql{i}"])
            for i, (seq, p) in enumerate(queued_meta)
        ],
    }

    # --- the restarted run -------------------------------------------
    # (Engine state restart is the serving snapshot's job, PR 5-tested;
    # here the restarted engine replays the applied prefix, then the
    # front door re-admits the spill in deterministic order.)
    eng2 = ArenaEngine(PLAYERS)
    applied = 0
    for (w, l), _producer in stream:
        if applied >= applied_before_crash:
            break
        eng2.ingest(w, l)
        applied += w.shape[0]
    assert applied == applied_before_crash
    fd2 = FrontDoor(eng2, capacity=64)
    fd2.resubmit_spilled(reloaded)
    fd2.flush()
    fd2.close()
    assert np.array_equal(np.asarray(eng2.ratings), np.asarray(eng_ref.ratings))
    assert eng2.matches_ingested == eng_ref.matches_ingested


def test_per_producer_streams_keep_the_producer_label():
    """The PR 7 metric schema holds under the front door: submitted
    batches are counted under their ORIGINAL producer label (the
    per-producer streams stay visible), drops and queue depth ride the
    same names, nothing was renamed."""
    rng = np.random.default_rng(5)
    obs = Observability()
    eng = ArenaEngine(PLAYERS, obs=obs)
    fd = FrontDoor(eng, capacity=64)
    try:
        for i in range(6):
            w, l = make_batch(rng)
            fd.submit(w, l, producer=f"frontend-{i % 3}")
        fd.flush()
    finally:
        fd.close()
    by_producer = obs.registry.counter_by_label(
        "arena_pipeline_submitted_batches_total", "producer"
    )
    assert by_producer == {
        "frontend-0": 2, "frontend-1": 2, "frontend-2": 2,
    }
    assert obs.registry.gauge(
        "arena_pipeline_queue_depth", producer="frontend-0"
    ).value >= 0.0


def test_admission_rejects_malformed_batches_with_no_state_change():
    eng = ArenaEngine(PLAYERS)
    fd = FrontDoor(eng)
    try:
        with pytest.raises(ValueError):
            fd.submit(np.array([0, 1], np.int32), np.array([1], np.int32))
        with pytest.raises(ValueError):
            fd.submit(
                np.array([PLAYERS], np.int32), np.array([0], np.int32)
            )
        with pytest.raises(ValueError):
            fd.submit(np.array([0], np.int32), np.array([1], np.int32),
                      producer="")
        assert fd.admitted_batches == 0
        assert eng.matches_ingested == 0
    finally:
        fd.close()


def test_merge_worker_error_surfaces_on_flush_not_a_hang():
    """A dead merge worker must raise FrontDoorError at the next
    flush/submit, never hang the caller (the pipeline's liveness
    discipline, inherited)."""
    rng = np.random.default_rng(6)
    eng = ArenaEngine(PLAYERS)
    fd = FrontDoor(eng)

    def boom(*args, **kwargs):
        raise RuntimeError("apply path died")

    eng.ingest_async = boom
    w, l = make_batch(rng)
    fd.submit(w, l)
    with pytest.raises(FrontDoorError, match="merge worker"):
        fd.flush()
    with pytest.raises(FrontDoorError):
        fd.submit(w, l)


def test_closed_front_door_rejects_submissions():
    eng = ArenaEngine(PLAYERS)
    fd = FrontDoor(eng)
    fd.close()
    with pytest.raises(FrontDoorError, match="closed"):
        fd.submit(np.array([0], np.int32), np.array([1], np.int32))
