"""Tests for verify_reference.py — the mechanical round-start gate.

Contract: exactly one JSON line on stdout; exit codes are distinct per
failure mode so exit-code-only consumers can never conflate them:
0 = live state matches the committed fingerprint; 1 = genuine drift
(reference tree non-empty, sidecar hashes changed, SNIPPETS.md
appearing); 2 = the fingerprint itself is missing or corrupt;
3 = transient environment failure (mount absent/unreadable/stale) —
NOT evidence the reference changed.

A non-empty observed tree must additionally produce a per-file manifest
(reference_manifest_observed.json) to bootstrap the mandated SURVEY.md
rewrite, without disturbing the one-line stdout contract.
"""

import hashlib
import json
import os
import pathlib

import bench
import verify_reference


def run_main(monkeypatch, capsys, reference, repo):
    """In-process ``python verify_reference.py``; returns (rc, result)."""
    monkeypatch.setenv("GRAFT_REFERENCE_PATH", str(reference))
    monkeypatch.setenv("GRAFT_REPO_PATH", str(repo))
    rc = verify_reference.main()
    captured = capsys.readouterr()
    assert captured.err == ""
    return rc, parse_single_json_line(captured.out)


def parse_single_json_line(stdout_text):
    lines = stdout_text.splitlines()
    assert len(lines) == 1
    return json.loads(lines[0])


def test_empty_reference_matches_fingerprint_exits_0(
    tmp_path, fake_repo, monkeypatch, capsys
):
    ref = tmp_path / "ref"
    ref.mkdir()
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_MATCH == 0
    assert result["reference_empty"] is True
    assert result["matches_fingerprint"] is True
    assert result["drift"] == []
    assert result["manifest"] is None
    assert not (fake_repo / verify_reference.MANIFEST_NAME).exists()


def test_populated_reference_is_drift_exits_1(tmp_path, fake_repo, monkeypatch, capsys):
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "main.cu").write_text("// code\n")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT == 1
    assert result["reference_empty"] is False
    assert result["matches_fingerprint"] is False
    assert result["transient_environment_failure"] is False
    assert "DRIFT" in result["note"]
    assert {d["fact"] for d in result["drift"]} == {"reference_entry_count"}
    assert result["observed"]["reference_entry_count"] == 2


def test_populated_reference_writes_manifest(tmp_path, fake_repo, monkeypatch, capsys):
    """The manifest must record every entry (dirs, files, symlinks) with
    relative path, type, size, and file sha256, sorted by path — the
    evidence bootstrap for rewriting SURVEY.md from a real tree."""
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "main.cu").write_text("// code\n")
    (ref / "README.md").write_text("hello\n")
    (ref / "link").symlink_to("README.md")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT

    manifest_path = fake_repo / verify_reference.MANIFEST_NAME
    assert result["manifest"] == str(manifest_path)
    assert not list(fake_repo.glob(verify_reference.MANIFEST_NAME + ".*.tmp"))
    manifest = json.loads(manifest_path.read_text())
    assert manifest["reference_path"] == str(ref)
    assert manifest["entry_count"] == 4
    assert [e["path"] for e in manifest["entries"]] == [
        "README.md",
        "link",
        "src",
        "src/main.cu",
    ]
    by_path = {e["path"]: e for e in manifest["entries"]}
    assert by_path["src"]["type"] == "dir"
    assert by_path["link"]["type"] == "symlink"
    assert by_path["link"]["target"] == "README.md"
    assert by_path["src/main.cu"]["type"] == "file"
    assert by_path["src/main.cu"]["size"] == len("// code\n")
    assert (
        by_path["src/main.cu"]["sha256"]
        == hashlib.sha256(b"// code\n").hexdigest()
    )


def test_unwritable_manifest_does_not_break_the_gate(
    tmp_path, fake_repo, deny_manifest_write, monkeypatch, capsys
):
    """If the manifest cannot be written (read-only repo dir), the gate
    still reports drift with rc 1 and one JSON line; the failure is
    surfaced as manifest_error instead of a crash, and the note must not
    point the reader at a manifest that was never written."""
    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["manifest"] is None
    assert result["manifest_error"] == "OSError"
    assert "manifest" not in result["note"]
    assert not list(fake_repo.glob(verify_reference.MANIFEST_NAME + "*"))


def test_unreadable_file_is_marked_in_manifest(tmp_path, fake_repo, monkeypatch, capsys):
    """A file whose contents cannot be read must carry an explicit error
    marker in the manifest — sha256:null alone is indistinguishable from
    a benign dir/symlink entry, which would make the evidence look
    complete when it is not."""
    ref = tmp_path / "ref"
    ref.mkdir()
    (ref / "ok.txt").write_text("fine\n")
    (ref / "broken.txt").write_text("secret\n")
    (ref / "badlink").symlink_to("ok.txt")
    real_read_bytes = pathlib.Path.read_bytes
    real_readlink = os.readlink

    def flaky_read_bytes(self):
        if self.name == "broken.txt":
            raise PermissionError("no read access")
        return real_read_bytes(self)

    def flaky_readlink(path, *args, **kwargs):
        if pathlib.Path(path).name == "badlink":
            raise OSError("stale handle")
        return real_readlink(path, *args, **kwargs)

    monkeypatch.setattr(pathlib.Path, "read_bytes", flaky_read_bytes)
    monkeypatch.setattr(os, "readlink", flaky_readlink)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    manifest = json.loads(
        (fake_repo / verify_reference.MANIFEST_NAME).read_text()
    )
    by_path = {e["path"]: e for e in manifest["entries"]}
    assert by_path["broken.txt"]["sha256"] is None
    assert by_path["broken.txt"]["error"] == "PermissionError"
    assert by_path["badlink"]["type"] == "symlink"
    assert by_path["badlink"]["target"] is None
    assert by_path["badlink"]["error"] == "OSError"
    assert by_path["ok.txt"]["sha256"] == hashlib.sha256(b"fine\n").hexdigest()
    assert "error" not in by_path["ok.txt"]


def test_matching_nonempty_fingerprint_retires_the_emptiness_note(
    tmp_path, monkeypatch, capsys
):
    """After a deliberate fingerprint update to a re-populated reference,
    a clean match (rc 0) must not keep claiming the reference is empty."""
    from conftest import make_fake_repo

    ref = tmp_path / "ref"
    (ref / "src").mkdir(parents=True)
    (ref / "src" / "main.cu").write_text("// code\n")
    repo = make_fake_repo(tmp_path, entry_count=2)
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_MATCH
    assert result["matches_fingerprint"] is True
    assert result["reference_empty"] is False
    assert "still empty" not in result["note"]
    assert "NON-EMPTY" in result["note"]
    assert (repo / verify_reference.MANIFEST_NAME).exists()


def test_sidecar_drift_during_mount_outage_is_drift_not_transient(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Genuine sidecar drift must exit 1 even when the mount is also
    unscannable this run — rc 3 would hide the drift from exit-code-only
    consumers, who would just retry the mount forever."""
    (fake_repo / "PAPERS.md").write_text("# PAPERS\n\nnew retrieved content\n")
    rc, result = run_main(monkeypatch, capsys, tmp_path / "gone", fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert result["transient_environment_failure"] is True
    assert {d["fact"] for d in result["drift"]} == {
        "papers_md_sha256",
        "reference_entry_count",
    }
    assert "DRIFT" in result["note"]
    assert "could not be scanned" in result["note"]


def test_missing_reference_is_transient_exits_3(tmp_path, fake_repo, monkeypatch, capsys):
    rc, result = run_main(monkeypatch, capsys, tmp_path / "gone", fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT == 3
    assert result["observed"]["reference_entry_count"] == "mount_missing_or_unreadable"
    # The exit code and the JSON evidence must both self-describe this as
    # environmental, not as the reference having changed (SKILL.md).
    assert result["transient_environment_failure"] is True
    assert "TRANSIENT" in result["note"]
    assert result["manifest"] is None


def test_scan_error_is_transient_exits_3(tmp_path, fake_repo, monkeypatch, capsys):
    """A mid-walk OSError (via the shared bench.scan) is a transient
    environment failure with its own exit code, not drift."""
    ref = tmp_path / "ref"
    bad = ref / "bad"
    bad.mkdir(parents=True)
    real_scandir = os.scandir

    def flaky_scandir(path=".", *args, **kwargs):
        if pathlib.Path(path) == bad:
            raise OSError("mount went stale mid-iteration")
        return real_scandir(path, *args, **kwargs)

    monkeypatch.setattr(os, "scandir", flaky_scandir)
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_TRANSIENT
    assert result["observed"]["reference_entry_count"] == "scan_error"
    assert result["transient_environment_failure"] is True


def test_changed_baseline_sidecar_is_drift_exits_1(
    tmp_path, fake_repo, monkeypatch, capsys
):
    ref = tmp_path / "ref"
    ref.mkdir()
    (fake_repo / "BASELINE.json").write_text('{"north_star": "now it has code!"}\n')
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert {d["fact"] for d in result["drift"]} == {"baseline_json_sha256"}
    # the reference itself is still empty; only the sidecar moved
    assert result["reference_empty"] is True
    assert result["manifest"] is None


def test_snippets_appearing_is_drift_exits_1(tmp_path, monkeypatch, capsys):
    from conftest import make_fake_repo

    ref = tmp_path / "ref"
    ref.mkdir()
    repo = make_fake_repo(tmp_path, with_snippets=True)
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_DRIFT
    assert {d["fact"] for d in result["drift"]} == {"snippets_md_present"}


def test_count_entries_delegates_to_bench(tmp_path):
    """bench.scan and the round-start gate must agree on the same mount,
    including when the caller hands over a precomputed scan result."""
    (tmp_path / "a").mkdir()
    (tmp_path / "a" / "b.txt").write_text("x")
    assert verify_reference.count_entries(tmp_path) == 2
    assert verify_reference.count_entries(tmp_path / "gone") == (
        "mount_missing_or_unreadable"
    )
    precomputed = bench.scan(tmp_path)
    assert verify_reference.count_entries(tmp_path, scan_result=precomputed) == 2


def test_missing_fingerprint_exits_2(tmp_path, monkeypatch, capsys):
    ref = tmp_path / "ref"
    ref.mkdir()
    repo = tmp_path / "bare"
    repo.mkdir()
    rc, result = run_main(monkeypatch, capsys, ref, repo)
    assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT == 2
    assert result["error"] == "fingerprint_missing_or_corrupt"


def test_corrupt_fingerprint_exits_2(tmp_path, fake_repo, monkeypatch, capsys):
    ref = tmp_path / "ref"
    ref.mkdir()
    (fake_repo / "reference_fingerprint.json").write_text("{not json")
    rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
    assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT
    assert result["error"] == "fingerprint_missing_or_corrupt"


def test_non_object_json_fingerprint_exits_2(tmp_path, fake_repo, monkeypatch, capsys):
    """Valid JSON that is not an object (null, list, scalar) is corrupt,
    not drift: must take the exit-2 path, not crash with rc 1."""
    ref = tmp_path / "ref"
    ref.mkdir()
    for payload in ("null", "[]", '"x"', "42"):
        (fake_repo / "reference_fingerprint.json").write_text(payload)
        rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
        assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT, payload
        assert result["error"] == "fingerprint_missing_or_corrupt"


def test_non_int_fingerprint_count_exits_2(tmp_path, fake_repo, monkeypatch, capsys):
    """A fingerprint whose reference_entry_count is not a non-negative
    int is corrupt. Otherwise an error sentinel pasted into the
    fingerprint (e.g. from an observed block captured during a mount
    outage) would make every future transient failure 'match' with rc 0
    and a verdict-retiring note."""
    fingerprint = json.loads((fake_repo / "reference_fingerprint.json").read_text())
    for bad_count in ("mount_missing_or_unreadable", "scan_error", None, -1, 1.5, True):
        fingerprint["reference_entry_count"] = bad_count
        (fake_repo / "reference_fingerprint.json").write_text(json.dumps(fingerprint))
        rc, result = run_main(monkeypatch, capsys, tmp_path / "gone", fake_repo)
        assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT, bad_count
        assert result["error"] == "fingerprint_missing_or_corrupt"


def test_invalid_fingerprint_sidecar_fields_exit_2(
    tmp_path, fake_repo, monkeypatch, capsys
):
    """Missing/null/mistyped sidecar facts are fingerprint corruption
    (rc 2: fix the repo), not sidecar drift (rc 1: verdict-affecting
    workflow) — the same asymmetry guard as for the entry count."""
    ref = tmp_path / "ref"
    ref.mkdir()
    good = json.loads((fake_repo / "reference_fingerprint.json").read_text())
    mutations = [
        ("baseline_json_sha256", None),
        ("papers_md_sha256", 42),
        ("snippets_md_present", "no"),
        ("baseline_json_sha256", "DELETE"),
    ]
    for key, value in mutations:
        fingerprint = dict(good)
        if value == "DELETE":
            del fingerprint[key]
        else:
            fingerprint[key] = value
        (fake_repo / "reference_fingerprint.json").write_text(json.dumps(fingerprint))
        rc, result = run_main(monkeypatch, capsys, ref, fake_repo)
        assert rc == verify_reference.EXIT_FINGERPRINT_CORRUPT, (key, value)
        assert result["error"] == "fingerprint_missing_or_corrupt"


def test_e2e_real_repo_fingerprint_matches_live_mount(e2e):
    """The documented round-start gate, run exactly as documented
    (plain ``python verify_reference.py``): the committed fingerprint
    must match the real repo sidecars, and the live mount must be
    empty (rc 0) or environmentally unavailable (rc 3). Any other
    outcome — in particular a NON-EMPTY remounted reference — fails
    this test loudly: SURVEY.md is then obsolete and must be rewritten
    from the real tree before any build work."""
    run = e2e["verify_real"]
    assert run.err == ""
    result = parse_single_json_line(run.out)
    # .get: the rc-2 outcome emits no drift key; the rc assertion below
    # must then fire with its diagnostic, not a KeyError here.
    sidecar_drift = [
        d for d in result.get("drift", []) if d["fact"] != "reference_entry_count"
    ]
    assert sidecar_drift == [], (
        "reference_fingerprint.json is stale relative to the committed "
        f"sidecars: {sidecar_drift}"
    )
    assert run.rc in (
        verify_reference.EXIT_MATCH,
        verify_reference.EXIT_TRANSIENT,
    ), f"unexpected gate outcome rc={run.rc}: {result}"
    if run.rc == verify_reference.EXIT_MATCH:
        assert result["matches_fingerprint"] is True
        assert result["observed"]["reference_entry_count"] == 0
    else:
        assert result["transient_environment_failure"] is True


def test_e2e_populated_reference_drift(e2e):
    """End-to-end subprocess run against a populated mount: rc 1, one
    JSON line, manifest written — through the real exit-code plumbing
    that round-start scripts consume."""
    run = e2e["verify_populated"]
    assert run.rc == verify_reference.EXIT_DRIFT
    assert run.err == ""
    result = parse_single_json_line(run.out)
    assert "DRIFT" in result["note"]
    assert result["observed"]["reference_entry_count"] == 3
    manifest_path = run.repo / verify_reference.MANIFEST_NAME
    assert manifest_path.exists()
    assert json.loads(manifest_path.read_text())["entry_count"] == 3
