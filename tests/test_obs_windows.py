"""Sliding-window aggregation contracts (arena/obs/windows.py).

The load-bearing properties:

- the ring ROTATES: a full-window read diffs against the OLDEST
  retained boundary, so counts recorded across multiple intervals all
  land in the window — the mutation audit carries a
  window-ring-never-rotates mutant (head frozen in place, so the ring
  holds only the newest boundary and every "window" collapses to the
  last interval); test_window_merges_counts_across_ring_intervals is
  its named kill;
- wraparound exactness: past `intervals` rotations the oldest history
  EXPIRES — the window is a window, not a second cumulative store;
- windowed quantiles agree with offline numpy over the same sample
  set to within one log2 bucket, across rotation and wraparound (the
  property the /debug/window p99 is trusted to have);
- windowed counter deltas are EXACT under N-thread concurrency (the
  same no-lost-updates discipline the cumulative registry pins);
- PR 10 liveness: a dead rotation thread is an explicit WindowError
  on every blocked wait and a non-None health()["error"] — never a
  silently frozen window.

All fake-clock driven (no sleeps on the rotation math); only the
liveness tests start the real thread.
"""

import threading

import numpy as np
import pytest

from arena.obs.metrics import Registry
from arena.obs.windows import NullWindow, SlidingWindow, WindowError


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def make_window(intervals=12, interval_s=5.0):
    reg = Registry()
    clock = FakeClock()
    win = SlidingWindow(
        reg, intervals=intervals, interval_s=interval_s, clock=clock
    )
    return reg, clock, win


# --- rotation correctness (the mutation-audit kill) ------------------------


def test_window_merges_counts_across_ring_intervals():
    """Counts recorded in DIFFERENT intervals all land in the full
    window: the read diffs against the oldest retained boundary, not
    the newest. Named kill for the audit's window-ring-never-rotates
    mutant (head frozen => ring[head] holds the NEWEST boundary and
    the 'full window' collapses to just the last interval)."""
    reg, clock, win = make_window(intervals=12, interval_s=5.0)
    c = reg.counter("arena_test_total")

    c.inc(10)
    clock.tick(5.0)
    assert win.advance() == 1
    c.inc(20)
    clock.tick(5.0)
    assert win.advance() == 1
    c.inc(30)

    full = win.delta()
    assert full.counter_delta("arena_test_total") == 60
    # The fast window (1 interval back) sees only the newest records.
    fast = win.delta(intervals=1)
    assert fast.counter_delta("arena_test_total") == 30
    assert win.health()["rotations"] == 2


def test_window_expires_history_past_the_ring():
    """After `intervals` further rotations with no new traffic, old
    counts leave the window entirely: a window, not a cumulative."""
    reg, clock, win = make_window(intervals=4, interval_s=1.0)
    c = reg.counter("arena_test_total")
    c.inc(100)
    for _ in range(5):
        clock.tick(1.0)
        win.advance()
    assert win.delta().counter_delta("arena_test_total") == 0
    # The cumulative registry still has everything (windows are reads,
    # never mutations of the underlying store).
    assert c.value == 100


def test_window_wraparound_is_exact():
    """Across many wraparounds the full window equals exactly the sum
    of the last `intervals` completed intervals plus the current
    partial one."""
    intervals = 4
    reg, clock, win = make_window(intervals=intervals, interval_s=1.0)
    c = reg.counter("arena_test_total")
    per_interval = []
    for k in range(11):
        c.inc(k + 1)
        per_interval.append(k + 1)
        clock.tick(1.0)
        win.advance()
        # Right after rotation r the window diffs against the boundary
        # `intervals` rotations back: seed (=everything) while the ring
        # is still filling, then exactly the last intervals-1 completed
        # intervals (the in-progress interval is empty here).
        rotations = k + 1
        expect = (
            sum(per_interval)
            if rotations <= intervals - 1
            else sum(per_interval[-(intervals - 1):])
        )
        assert win.delta().counter_delta("arena_test_total") == expect
    # Mid-interval partial rides on top of the completed spans.
    c.inc(1000)
    assert win.delta().counter_delta("arena_test_total") == (
        sum(per_interval[-(intervals - 1):]) + 1000
    )


def test_multi_interval_clock_jump_rotates_every_crossed_boundary():
    """A clock jump over n boundaries rotates n slots (capped at the
    ring) in ONE advance — a stalled reader catching up must expire
    history exactly as if it had rotated on time."""
    reg, clock, win = make_window(intervals=4, interval_s=1.0)
    c = reg.counter("arena_test_total")
    c.inc(7)
    clock.tick(2.5)  # crosses 2 boundaries at once
    assert win.advance() == 2
    assert win.health()["rotations"] == 2
    assert win.delta().counter_delta("arena_test_total") == 7
    clock.tick(10.0)  # way past the whole ring
    win.advance()
    assert win.delta().counter_delta("arena_test_total") == 0


# --- windowed quantiles vs offline numpy -----------------------------------


def test_windowed_percentile_matches_numpy_within_one_bucket():
    """Property: across rotation and wraparound, the windowed p50/p90/
    p99 land within one log2 bucket of the offline numpy percentile
    computed over exactly the samples still in the window."""
    reg = Registry()
    clock = FakeClock()
    intervals, interval_s = 4, 1.0
    win = SlidingWindow(
        reg, intervals=intervals, interval_s=interval_s, clock=clock
    )
    hist = reg.histogram("arena_test_seconds", base=1.0)
    rng = np.random.default_rng(7)
    interval_samples = [[]]  # newest last; [-1] is the current partial
    for step in range(10):
        vals = rng.lognormal(mean=2.0, sigma=1.5, size=200)
        for v in vals:
            hist.record(float(v))
            interval_samples[-1].append(float(v))
        # Window = everything while the ring is still filling, then the
        # last intervals-1 completed chunks + the current partial one.
        rotations = step
        live = (
            interval_samples
            if rotations <= intervals - 1
            else interval_samples[-intervals:]
        )
        in_window = np.asarray([v for chunk in live for v in chunk])
        wh = win.delta().histogram("arena_test_seconds")
        assert wh.count == in_window.size
        for q in (0.5, 0.9, 0.99):
            got = wh.percentile(q)
            ref = float(np.percentile(in_window, q * 100))
            idx_got = int(np.searchsorted(hist.bounds, got, side="left"))
            idx_ref = int(np.searchsorted(hist.bounds, ref, side="left"))
            assert abs(idx_got - idx_ref) <= 1, (
                f"step {step} q={q}: windowed {got} vs numpy {ref} "
                f"(buckets {idx_got} vs {idx_ref})"
            )
        clock.tick(interval_s)
        win.advance()
        interval_samples.append([])


def test_windowed_histogram_sum_and_rate():
    reg, clock, win = make_window(intervals=3, interval_s=2.0)
    hist = reg.histogram("arena_test_seconds", base=1.0)
    for v in (1.0, 2.0, 3.0):
        hist.record(v)
    clock.tick(2.0)
    win.advance()
    hist.record(10.0)
    wh = win.delta().histogram("arena_test_seconds")
    assert wh.count == 4
    assert wh.sum == pytest.approx(16.0)
    # Rate over the window's elapsed span (2 completed + 0 partial s).
    assert wh.rate_per_s == pytest.approx(4 / wh.elapsed_s)


# --- exactness under concurrency -------------------------------------------


def test_windowed_counter_is_exact_under_n_threads():
    """8 threads x 500 increments with rotations interleaved lose
    NOTHING: the full-window delta equals the arithmetic total (the
    window must inherit the registry's exactness, not sample it)."""
    reg, clock, win = make_window(intervals=12, interval_s=5.0)
    c = reg.counter("arena_test_total")
    threads, per_thread = 8, 500
    barrier = threading.Barrier(threads)

    def worker():
        barrier.wait()
        for _ in range(per_thread):
            c.inc()

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    for t in ts:
        t.start()
    # Rotate a few times while the writers hammer (fewer rotations
    # than the ring holds, so nothing expires).
    for _ in range(3):
        clock.tick(5.0)
        win.advance()
    for t in ts:
        t.join()
    assert win.delta().counter_delta("arena_test_total") == (
        threads * per_thread
    )


# --- reads, payloads, twins ------------------------------------------------


def test_read_payload_shape_and_label_match():
    reg, clock, win = make_window(intervals=2, interval_s=1.0)
    reg.counter("arena_test_total", endpoint="a").inc(3)
    reg.counter("arena_test_total", endpoint="b").inc(4)
    reg.gauge("arena_test_depth").set(9)
    out = win.read()
    assert set(out) == {
        "window_s", "counters", "gauges", "histograms", "ring"
    }
    assert out["counters"]['arena_test_total{endpoint="a"}']["delta"] == 3
    assert out["gauges"]["arena_test_depth"] == 9
    assert out["ring"]["mode"] == "on-read"
    assert out["ring"]["error"] is None
    # Label matching merges across series; prefix patterns match too.
    d = win.delta()
    assert d.counter_delta("arena_test_total") == 7
    assert d.counter_delta("arena_test_total", {"endpoint": "a"}) == 3
    assert d.counter_delta("arena_test_total", {"endpoint": "*"}) == 7


def test_null_window_is_a_true_noop_twin():
    null = NullWindow()
    assert null.start() is null
    assert null.advance() == 0
    assert null.delta().counter_delta("anything") == 0
    assert null.delta().histogram("anything").count == 0
    assert null.read()["ring"]["error"] is None
    assert null.wait_for_rotation() == 0
    null.close()


def test_window_rejects_malformed_shape():
    reg = Registry()
    with pytest.raises(WindowError):
        SlidingWindow(reg, intervals=0)
    with pytest.raises(WindowError):
        SlidingWindow(reg, interval_s=0.0)


# --- PR 10 liveness discipline ---------------------------------------------


def test_rotation_thread_rotates_for_real():
    reg = Registry()
    win = SlidingWindow(reg, intervals=4, interval_s=0.02)
    win.start()
    try:
        assert win.wait_for_rotation(rotations=2, timeout=10.0) >= 2
        assert win.health()["mode"] == "thread"
        assert win.health()["error"] is None
    finally:
        win.close()
    # A clean close is NOT an error; reads continue in on-read mode.
    assert win.health()["error"] is None
    assert win.health()["mode"] == "on-read"
    # And start() is a restart, not a one-shot.
    win.start()
    try:
        win.wait_for_rotation(rotations=1, timeout=10.0)
    finally:
        win.close()


def test_dead_rotator_raises_instead_of_hanging():
    """PR 10 discipline: a rotation thread that died mid-run surfaces
    as an explicit WindowError from every blocked wait and a non-None
    health error — never a silently frozen window."""
    reg = Registry()
    win = SlidingWindow(reg, intervals=4, interval_s=0.01)

    def boom():
        raise RuntimeError("snapshot exploded")

    win._snap_cumulative = boom  # instance shadow: next rotation dies
    win.start()
    try:
        with pytest.raises(WindowError, match="rotation thread died"):
            win.wait_for_rotation(rotations=1, timeout=10.0)
        health = win.health()
        assert health["error"] is not None
        assert "snapshot exploded" in health["error"]
    finally:
        win.close()


def test_wait_for_rotation_without_thread_is_an_error():
    reg, _clock, win = make_window()
    with pytest.raises(WindowError, match="no rotation thread"):
        win.wait_for_rotation(timeout=0.2)
