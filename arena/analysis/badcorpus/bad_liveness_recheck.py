"""jaxlint corpus: a wait loop that trusts the worker to still be alive.

`flush()` waits for the packer thread to set `_done` — but if the
worker died with an exception, nothing ever notifies and the loop
spins on the condition FOREVER instead of raising. Every blocking wait
on worker progress must re-check `.is_alive()` each wakeup (the
`_check_packer_locked` shape arena/pipeline.py uses). Rule:
thread-no-liveness-recheck."""

import threading


class OneShotPacker:
    def __init__(self):
        self._cv = threading.Condition()
        self._done = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        with self._cv:
            self._done = True
            self._cv.notify_all()

    def flush(self):
        with self._cv:
            while not self._done:
                self._cv.wait(0.05)  # a dead worker hangs this forever
