"""Mechanical round-start verification that the reference is (still) empty.

The single load-bearing fact of this repository is that the upstream
`mark1222/arena` tree mounted at /root/reference contains zero files
(SURVEY.md), which makes the repo non-graftable (NON_GRAFTABLE.md,
BASELINE.json north star). Rounds 1-2 re-established that fact by
hand-run checklists; this script makes the gate mechanical, per
VERDICT.md "Next round" items 1, 4 and 5.

It re-runs the SURVEY.md verification checks and compares the results
against the committed fingerprint (reference_fingerprint.json):

- recursive entry count under the reference mount (guarded against the
  mount going stale mid-walk);
- mount stat facts (mode, link count, timestamps) — recorded as
  evidence only, NOT compared: the mount is recreated every round, so
  timestamps legitimately differ while content facts must not;
- sha256 of the driver sidecars BASELINE.json and PAPERS.md, and the
  presence/absence of SNIPPETS.md — retrieved public content appearing
  mid-project is the most likely vector for accidentally "discovering"
  capabilities the reference never had, so sidecar drift is surfaced
  explicitly (it does NOT by itself change what there is to build:
  only the mounted tree defines capabilities).

Output: exactly ONE JSON line on stdout with the evidence and a `drift`
list. Exit codes: 0 = everything matches the fingerprint (reference
still empty, sidecars unchanged); 1 = drift detected (reference
non-empty or changed sidecars — SURVEY.md may be obsolete; rewrite it
from the real tree before writing any code); 2 = could not gather
evidence (fingerprint missing/corrupt).

Paths are overridable for tests: GRAFT_REFERENCE_PATH (mount) and
GRAFT_REPO_PATH (directory holding the fingerprint and sidecars).
"""

import hashlib
import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
import bench  # the accessibility check + guarded walk live in ONE place

DEFAULT_REFERENCE = "/root/reference"
COMPARED_KEYS = (
    "reference_entry_count",
    "baseline_json_sha256",
    "papers_md_sha256",
    "snippets_md_present",
)


def sha256_of(path: pathlib.Path):
    try:
        return hashlib.sha256(path.read_bytes()).hexdigest()
    except OSError:
        return None


def count_entries(reference: pathlib.Path):
    """Recursive entry count, or an error-string sentinel on failure.

    Delegates to bench.scan() so the mount-accessibility check and the
    OSError-guarded walk exist in exactly one place; bench and this gate
    can never disagree about whether the same mount is empty.
    """
    result = bench.scan(reference)
    if result["metric"] == "non_graftable_reference_is_empty":
        return result["value"]
    if result["metric"] == "reference_scan_error":
        return "scan_error"
    return "mount_missing_or_unreadable"


def mount_stat(reference: pathlib.Path):
    """Informational stat facts (not compared — mount is recreated per round)."""
    try:
        st = reference.stat()
        return {
            "mode": oct(st.st_mode),
            "nlink": st.st_nlink,
            "size": st.st_size,
            "mtime": st.st_mtime,
        }
    except OSError as exc:
        return {"error": exc.__class__.__name__}


def gather(reference: pathlib.Path, repo: pathlib.Path) -> dict:
    return {
        "reference_entry_count": count_entries(reference),
        "baseline_json_sha256": sha256_of(repo / "BASELINE.json"),
        "papers_md_sha256": sha256_of(repo / "PAPERS.md"),
        "snippets_md_present": (repo / "SNIPPETS.md").exists(),
    }


def main() -> int:
    reference = pathlib.Path(os.environ.get("GRAFT_REFERENCE_PATH", DEFAULT_REFERENCE))
    repo = pathlib.Path(
        os.environ.get("GRAFT_REPO_PATH", pathlib.Path(__file__).resolve().parent)
    )

    try:
        fingerprint = json.loads((repo / "reference_fingerprint.json").read_text())
        if not isinstance(fingerprint, dict):
            raise ValueError("fingerprint must be a JSON object")
    except (OSError, ValueError):
        print(
            json.dumps(
                {
                    "check": "reference_verification",
                    "error": "fingerprint_missing_or_corrupt",
                    "fingerprint_path": str(repo / "reference_fingerprint.json"),
                }
            )
        )
        return 2

    observed = gather(reference, repo)
    drift = [
        {"fact": key, "fingerprint": fingerprint.get(key), "observed": observed[key]}
        for key in COMPARED_KEYS
        if observed[key] != fingerprint.get(key)
    ]
    transient = observed["reference_entry_count"] in (
        "mount_missing_or_unreadable",
        "scan_error",
    )

    if not drift:
        note = "reference still empty; non-graftable verdict stands"
    elif transient:
        note = (
            "TRANSIENT ENVIRONMENT FAILURE: the mount could not be scanned "
            "(absent, unreadable, or going stale mid-walk). This is NOT "
            "evidence the reference changed — there is no tree to re-survey. "
            "Investigate the mount / re-run; do not touch SURVEY.md."
        )
    else:
        note = (
            "DRIFT: the surveyed state changed. If the reference tree is "
            "non-empty, SURVEY.md is obsolete — rewrite it from the real tree "
            "before writing any code. Sidecar-only drift (PAPERS/SNIPPETS) "
            "does not add capabilities: only the mounted tree defines what "
            "to build."
        )

    result = {
        "check": "reference_verification",
        "reference_path": str(reference),
        "reference_empty": observed["reference_entry_count"] == 0,
        "matches_fingerprint": not drift,
        "transient_environment_failure": transient,
        "drift": drift,
        "observed": observed,
        "mount_stat": mount_stat(reference),
        "note": note,
    }
    print(json.dumps(result))
    return 0 if not drift else 1


if __name__ == "__main__":
    sys.exit(main())
