"""HTTP/JSON wire server: the network face of `ArenaServer`.

A stdlib `ThreadingHTTPServer` (no new dependencies) exposing the
already-JSON-shaped serving responses over six endpoints:

    GET  /healthz                     liveness + applied watermark
    GET  /leaderboard?offset=&limit=  one descending-rating page
    GET  /player/{id}                 one player's rating row (+ CI)
    GET  /h2h?a=&b=                   Elo P(a beats b)
    POST /submit                      admit one batch at the front door
    GET  /stats                       the registry's Prometheus render()
    GET  /debug/window                sliding-window rates + quantiles
    GET  /debug/slo                   burn-rate evaluation, alert states
    GET  /debug/profile               sampled stacks by thread role
    GET  /debug/trace/{id}            one trace's spans, oldest first

The /debug family is the live ops plane (PR 13): the same envelope,
span, and counter treatment as every other endpoint (the audit's
debug-endpoint-omits-envelope mutant pins that), served from the
`Observability` the registry already lives in. `start()` starts the
ops-plane threads (window rotation + profiler sampling) next to the
accept loop; `close()` stops them.

One request reads ONE immutable `ServingView` (the `ArenaServer.query`
contract — the handler never touches engine internals), and every JSON
response carries the staleness ``watermark`` with the request's
``trace_id`` next to it (`arena.net.protocol.make_response`); `/stats`
is Prometheus text, so its pair rides the `X-Arena-Watermark` /
`X-Arena-Trace-Id` headers instead (all endpoints set both headers).

Each request runs under a `net.<endpoint>` root span, so the serving
spans it triggers (view build, query) — and, for `/submit`, the whole
cross-thread admission → merge → pack → dispatch chain — reconstruct
as one trace from the id in the response. Requests land in
`arena_http_requests_total{endpoint=,status=}` and the per-endpoint
latency histogram through the server's ONE registry (the same schema
`stats()`, `/stats`, and the frontend bench read).

Threading: `ThreadingHTTPServer` gives one daemon thread per
connection (HTTP/1.1 keep-alive, so a frontend holds one thread, not
one per request). Query handlers are read-only against immutable
views; `/submit` serializes through the front door's admission lock.
The jitted work never runs on a handler thread — submit hands the
batch to the front door's merge worker and returns the ticket.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from arena.net import protocol

# Submit responses are 202 (accepted into the total order, applied
# asynchronously) — the wire mirrors the front door's semantics.
STATUS_ACCEPTED = 202


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # The wire tier logs through the metrics registry, not stderr.
    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        return None

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    # --- request plumbing --------------------------------------------

    def _handle(self, method):
        wire = self.server.wire
        obs = wire.obs
        t0 = time.perf_counter()
        endpoint = "unmatched"
        trace_id = 0
        # Drain the request body FIRST, unconditionally: on a keep-
        # alive connection an unread body would be parsed as the next
        # request's request line (every error path would poison the
        # connection behind it).
        length = int(self.headers.get("Content-Length") or 0)
        body_raw = self.rfile.read(length) if length else b""
        try:
            endpoint, params = protocol.parse_path(method, self.path)
            with obs.span(f"net.{endpoint}") as root:
                trace_id = root.trace_id
                status, payload = self._dispatch(
                    wire, endpoint, params, body_raw
                )
        except protocol.ProtocolError as exc:
            status, payload = exc.status, {"error": str(exc)}
        except ValueError as exc:
            # The serving/admission reject posture (bad ids, malformed
            # arrays): the caller's fault, named, no state change.
            status, payload = 400, {"error": str(exc)}
        except Exception as exc:  # noqa: BLE001 — a handler crash must
            # degrade to a structured 500, never a dropped connection.
            status, payload = 500, {"error": f"{type(exc).__name__}: {exc}"}
        watermark = wire.server.engine.matches_applied
        if payload is None:  # /stats: Prometheus text, envelope in headers
            body = wire.render().encode("utf-8")
            content_type = "text/plain; version=0.0.4"
        else:
            body = json.dumps(
                protocol.make_response(
                    payload, watermark=watermark, trace_id=trace_id
                )
            ).encode("utf-8")
            content_type = "application/json"
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Arena-Watermark", str(watermark))
            self.send_header("X-Arena-Trace-Id", str(trace_id))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionError):
            status = 499  # client went away mid-response (nginx's code)
        obs.counter(
            "arena_http_requests_total", endpoint=endpoint, status=str(status)
        ).inc()
        obs.histogram(
            "arena_http_request_latency_seconds", endpoint=endpoint
        ).record(time.perf_counter() - t0, trace_id=trace_id)

    def _dispatch(self, wire, endpoint, params, body_raw):
        srv = wire.server
        if endpoint == "healthz":
            return 200, {
                "status": "ok",
                "players": srv.engine.num_players,
                "matches_ingested": srv.engine.matches_ingested,
            }
        if endpoint == "stats":
            return 200, None  # body rendered from the registry
        if endpoint == "leaderboard":
            return 200, srv.query(
                leaderboard=(params["offset"], params["limit"])
            )
        if endpoint == "player":
            return 200, srv.query(players=[params["player"]])
        if endpoint == "h2h":
            return 200, srv.query(pairs=[(params["a"], params["b"])])
        if endpoint == "submit":
            return self._submit(wire, body_raw)
        if endpoint == "debug_window":
            return 200, wire.obs.windows.read()
        if endpoint == "debug_slo":
            return 200, wire.obs.slo.evaluate()
        if endpoint == "debug_profile":
            return 200, wire.obs.profiler.snapshot()
        if endpoint == "debug_trace":
            return 200, self._trace_payload(wire, params["trace_id"])
        raise protocol.ProtocolError(404, f"no such endpoint: {endpoint!r}")

    def _trace_payload(self, wire, trace_id):
        """Resolve one trace id (a response's `trace_id`, an SLO
        alert's exemplar) into its recorded spans. 404 when the ring
        kept nothing for it — evicted or never allocated. The payload
        key is `queried_trace_id`: the envelope's own `trace_id` slot
        belongs to THIS request's trace, authoritatively."""
        spans = wire.obs.tracer.trace(trace_id)
        if not spans:
            raise protocol.ProtocolError(
                404, f"no spans recorded for trace {trace_id}"
            )
        return {
            "queried_trace_id": trace_id,
            "spans": [
                {
                    "name": r.name,
                    "start": r.start,
                    "duration": r.duration,
                    "tid": r.tid,
                    "span_id": r.span_id,
                    "parent_id": r.parent_id,
                }
                for r in spans
            ],
        }

    def _submit(self, wire, body_raw):
        frontdoor = wire.frontdoor
        if frontdoor is None:
            raise protocol.ProtocolError(
                503, "this server has no front door (read-only replica)"
            )
        winners, losers, producer = protocol.parse_submit_body(body_raw)
        seq = frontdoor.submit(winners, losers, producer=producer)
        return STATUS_ACCEPTED, {
            "seq": seq,
            "producer": producer,
            "matches": int(winners.shape[0]),
            "pending_batches": frontdoor.pending_batches(),
        }


class ArenaHTTPServer:  # protocol: start->close
    """The wire tier: one `ThreadingHTTPServer` over one `ArenaServer`
    (+ optionally one `FrontDoor` for the submit path; without one the
    server is a read-only replica and /submit answers 503).

    `port=0` binds an ephemeral port (tests/bench); `self.port` is the
    bound one either way. `start()` serves on a daemon thread;
    `close()` shuts down and joins. Usable as a context manager."""

    def __init__(self, server, frontdoor=None, host="127.0.0.1", port=0):
        self.server = server
        self.frontdoor = frontdoor
        self.obs = server.obs
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.wire = self
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = None

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def render(self):
        """The /stats body: the registry's Prometheus exposition."""
        return self.obs.render()

    def start(self):
        if self._thread is not None:
            raise RuntimeError("wire server already started")
        # The ops plane serves live at /debug/*: rotation + sampling
        # threads ride the wire server's lifecycle (no-op on NULL obs).
        self.obs.start_ops()
        try:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.05},
                name="arena-wire-server",
                daemon=True,
            )
            self._thread.start()
        except BaseException:
            # A failed spawn must not strand the rotation/sampling
            # threads start_ops just launched: nobody holds a handle to
            # call close() on a server that never started.
            self._thread = None
            self.obs.stop_ops()
            raise
        return self

    def close(self):
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=10.0)
            self._thread = None
        self._httpd.server_close()
        self.obs.stop_ops()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
