"""Flight recorder: one atomic postmortem bundle instead of a bare rc 2.

When a HARD bench gate fires (equivalence divergence, instrumentation
overhead, a recompile in the soak's steady state) the one-JSON-line
contract gives the driver a verdict — but a human debugging the
failure needs the evidence that was live in the process at that
moment: the span ring (what every thread was doing), the full metrics
registry (every counter/histogram, exemplars included), the run's
configuration, and the recent structured events (drops, spills, the
queue-depth timeline). `dump_debug_bundle()` captures all of it as one
directory:

    <path>/
      MANIFEST.json   what's here + trace/event accounting
      trace.json      Chrome trace-event export (chrome://tracing)
      metrics.json    full registry dump (counters/gauges/histograms)
      config.json     caller-provided run configuration
      events.json     recent events + extracted queue-depth timeline
      profile.txt     collapsed sampling-profiler stacks by thread
                      role (empty when the ops plane never sampled)

The write is ATOMIC at the directory level: everything lands in a
`<path>.tmp` sibling first and the complete directory is renamed into
place last, so a crash mid-dump leaves no half-bundle at `path` (the
same torn-write discipline as the serving snapshot's manifest-last
ordering). An existing bundle at `path` is replaced.

Every hard bench gate (`arena/bench_arena.py` soak/serve/pipeline/
ingest modes) calls this on failure and ships the bundle path in its
rc-2 JSON line (`"debug_bundle"`), turning "the gate fired" into "the
gate fired, and here is the process's last flight". No jax imports
(the arena/obs rule); stdlib + the passed-in observability handle
only.
"""

import json
import pathlib
import shutil
import time


def dump_debug_bundle(obs, path, config=None):
    """Write one postmortem bundle for `obs` at directory `path`.

    `obs` is an `arena.obs.Observability` (a null instance produces an
    honest mostly-empty bundle); `config` is any JSON-able dict worth
    having next to the evidence (bench params, env knobs). Returns the
    final `pathlib.Path`. Atomic: `path` either holds the previous
    complete bundle or the new complete bundle, never a partial one.
    """
    path = pathlib.Path(path)
    tmp = path.with_name(path.name + ".tmp")
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    (tmp / "trace.json").write_text(obs.tracer.export_chrome_trace_json())
    (tmp / "metrics.json").write_text(
        json.dumps(obs.registry.dump(), indent=1, sort_keys=True)
    )
    (tmp / "config.json").write_text(
        json.dumps(config or {}, indent=1, sort_keys=True, default=str)
    )
    events = list(obs.events)
    (tmp / "events.json").write_text(json.dumps({
        "events": events,
        # The queue-depth timeline, extracted for direct plotting:
        # (monotonic seconds, depth) per submit-path sample.
        "queue_depth_timeline": [
            [e["t"], e["depth"]]
            for e in events
            if e.get("kind") == "queue_depth" and "depth" in e
        ],
    }, indent=1))
    # Collapsed stacks from the continuous sampling profiler (PR 13).
    # Pre-ops-plane Observability objects lack the attribute; a bundle
    # from one still writes the file so the layout never varies.
    profiler = getattr(obs, "profiler", None)
    (tmp / "profile.txt").write_text(
        profiler.collapsed() if profiler is not None else ""
    )
    # The static-analysis state of the tree at failure time: a
    # full-registry jaxlint run over the default targets, as SARIF.
    # A postmortem diff of two bundles then shows whether the tree's
    # lint surface moved between the runs. Imported lazily — jaxlint
    # is jax-free stdlib, but this module's import-time contract is
    # stdlib-only.
    from arena.analysis import jaxlint
    (tmp / "lint.sarif").write_text(jaxlint._sarif_report(
        jaxlint.lint_paths(jaxlint.default_targets(), keep_suppressed=True)
    ))
    (tmp / "MANIFEST.json").write_text(json.dumps({
        "bundle": "arena-debug",
        "written_at_unix": time.time(),
        "files": ["trace.json", "metrics.json", "config.json",
                  "events.json", "profile.txt", "lint.sarif"],
        "spans_recorded": obs.tracer.recorded,
        "trace_dropped": obs.tracer.dropped,
        "events_recorded": len(events),
        "profiler_samples": (
            profiler.samples if profiler is not None else 0
        ),
    }, indent=1, sort_keys=True))
    if path.exists():
        shutil.rmtree(path)
    tmp.rename(path)
    return path
