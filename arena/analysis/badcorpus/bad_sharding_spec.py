"""jaxlint corpus: shard_map specs that disagree with the mesh.

The mesh defines exactly one axis ("data"); the in_specs tuple names a
"model" axis no mesh defines AND carries two specs for a three-argument
function — both silent until runtime (or until an unlucky shape makes
them loud). Rule: sharding-spec-arity."""

from functools import partial

import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"

mesh = Mesh(np.array(jax.devices()), (DATA_AXIS,))


@partial(
    shard_map,
    mesh=mesh,
    in_specs=(P(DATA_AXIS), P("model")),  # unknown axis; 2 specs, 3 args
    out_specs=P(),
)
def bad_sharded(a, b, c):
    return a + b + c
