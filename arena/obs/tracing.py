"""Span tracing: monotonic-clock stage spans in a bounded ring buffer.

Where `arena/obs/metrics.py` answers "how much / how fast overall",
spans answer "where did THIS request's time go": every pipeline stage
(enqueue wait, pack, CSR merge, compaction, staging, jit dispatch,
apply) and every serving operation (view build, query, snapshot,
restore) wraps itself in `tracer.span(name)` — a context manager that
reads `time.perf_counter()` on enter and exit and records one
fixed-size row into preallocated ring arrays.

Honest-timing note: spans time HOST stages — work that is complete
when `__exit__` runs (NumPy packing, lock waits, file IO, dispatch
issue). They are NOT a device-time measurement: a span around an
asynchronous jax dispatch measures dispatch issue cost, which is the
host-side quantity the pipeline overlaps (the bench's wall-clock
numbers, which DO include device time, keep their explicit
`block_until_ready` discipline — the jaxlint `timing-without-block`
rule polices that, and a corpus example shows the hand-rolled version
of this pattern being flagged while this API is not: the clock reads
live inside `_Span`, not interleaved with the caller's dispatches).

The ring is bounded and overwrite-oldest: a long soak keeps the NEWEST
`capacity` spans and counts what it dropped (`dropped` — exposed as
the `trace_dropped` counter in dumps), so tracing can stay on in
production without growing memory. Export is Chrome trace-event JSON
(`chrome://tracing`, Perfetto): complete "X" events with microsecond
timestamps, one row per span, thread id preserved.

No jax imports (same rule as the metrics half).
"""

import json
import threading
import time


class _Span:
    """One live span: clock read on enter, row recorded on exit."""

    __slots__ = ("_tracer", "_name", "_t0")

    def __init__(self, tracer, name):
        self._tracer = tracer
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        self._tracer.record_span(self._name, self._t0, t1 - self._t0)
        return False


class Tracer:
    """Bounded ring buffer of completed spans.

    `capacity` rows are preallocated (name slots + float start/duration
    arrays + int thread ids); recording wraps around, overwriting the
    oldest row and incrementing `dropped` — newest-wins, fixed memory.
    All mutation happens under one small lock (a span record is a few
    list/scalar stores; contention is negligible next to the stages
    being traced).
    """

    def __init__(self, capacity=4096):
        if capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._names = [None] * capacity
        self._starts = [0.0] * capacity
        self._durs = [0.0] * capacity
        self._tids = [0] * capacity
        self._n = 0  # total ever recorded
        self.dropped = 0  # rows overwritten (n - capacity, floored at 0)
        self._lock = threading.Lock()

    @property
    def recorded(self):
        """Total spans ever recorded (kept + dropped)."""
        return self._n

    def span(self, name):
        """Context manager timing one named host stage."""
        return _Span(self, name)

    def record_span(self, name, start, duration, tid=None):
        """Record one completed span (the non-context-manager form, for
        stages whose start/end cross function boundaries — e.g. the
        pipeline's enqueue wait)."""
        if tid is None:
            tid = threading.get_ident()
        with self._lock:
            i = self._n % self.capacity
            self._names[i] = name
            self._starts[i] = start
            self._durs[i] = duration
            self._tids[i] = tid
            self._n += 1
            if self._n > self.capacity:
                self.dropped += 1

    def spans(self):
        """Kept spans, oldest first: (name, start_s, duration_s, tid)."""
        with self._lock:
            n = min(self._n, self.capacity)
            head = self._n % self.capacity
            order = (
                list(range(head, self.capacity)) + list(range(head))
                if self._n > self.capacity
                else list(range(n))
            )
            return [
                (self._names[i], self._starts[i], self._durs[i], self._tids[i])
                for i in order
            ]

    def export_chrome_trace(self):
        """Chrome trace-event list: complete ("X") events, microsecond
        units, loadable by chrome://tracing and Perfetto."""
        return [
            {
                "name": name,
                "ph": "X",
                "ts": round(start * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "pid": 0,
                "tid": tid,
            }
            for name, start, dur, tid in self.spans()
        ]

    def export_chrome_trace_json(self):
        return json.dumps({"traceEvents": self.export_chrome_trace()})


class _NullSpan:
    """Singleton no-op context manager (zero allocation per span)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


class NullTracer:
    """No-op twin of `Tracer`: `span()` hands back one shared no-op
    context manager, nothing is ever recorded or allocated."""

    capacity = 0
    dropped = 0
    recorded = 0
    _SPAN = _NullSpan()

    def span(self, name):
        return self._SPAN

    def record_span(self, name, start, duration, tid=None):
        return None

    def spans(self):
        return []

    def export_chrome_trace(self):
        return []

    def export_chrome_trace_json(self):
        return '{"traceEvents": []}'
