"""jaxlint corpus: a guarded field checked and acted on in two
critical sections.

`_seats` is `# guarded_by: _lock`, and every individual access here
IS lock-held — PR 10's unguarded-shared-write has nothing to say. The
race is between the sections: the check reads `seats` under the lock,
releases it, and the act decrements from the STALE copy, so two
threads that both saw `seats == 1` both book it. The check and the
act must share one critical section (or re-read after re-acquiring).
Rule: check-then-act-race.
"""

import threading


class Booker:
    def __init__(self):
        self._lock = threading.Lock()
        self._seats = 8  # guarded_by: _lock

    def book(self):
        with self._lock:
            seats = self._seats  # the check...
        if seats > 0:  # ...acted on after the lock was released
            with self._lock:
                self._seats = seats - 1  # lost update: seats is stale
            return True
        return False
