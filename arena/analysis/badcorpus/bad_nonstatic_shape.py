"""jaxlint corpus: shape-derived scalars flowing into jit arguments.

`batch.shape[0]` changes with every distinct batch size; without
static_argnums (or the engine's pow2 bucketing) each size means a new
trace. Rule: nonstatic-shape-arg."""

import jax


def _kernel(x, n):
    return x * n


apply_kernel = jax.jit(_kernel)


def rescale(batch):
    n = batch.shape[0]
    return apply_kernel(batch, n)


def rescale_direct(batch):
    return apply_kernel(batch, batch.shape[0])
