"""jaxlint corpus: a versioned serialized format drifts silently.

`write_manifest` is contracted to `corpus-manifest@v1`, whose sidecar
(`schemas/corpus-manifest.json`) records fields {magic, version,
num_rows} behind the `CORPUS_MANIFEST_VERSION` constant. The writer
now also emits `row_digest` — but the constant still says 1, so every
deployed reader of v1 manifests meets a shape it never agreed to.
Rule: schema-drift-without-version-bump.
"""

CORPUS_MANIFEST_VERSION = 1


def write_manifest(store):  # schema: corpus-manifest@v1
    return {
        "magic": "CORPUS",
        "version": CORPUS_MANIFEST_VERSION,
        "num_rows": store.num_rows,
        "row_digest": store.digest(),
    }
