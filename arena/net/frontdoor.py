"""Multi-producer front door: admission, total order, bounded shedding.

`IngestPipeline` (PR 4) assumes ONE producer: its FIFO queue order IS
the submission order, which is what makes async ingest bit-exact to
sync. Real arena traffic has many concurrent frontends on the submit
path (ROADMAP item 1), and "whatever order the threads happened to
interleave" is not a replayable order. This module generalizes the
submit path without giving that property up:

1. **Admission = the total order.** Every batch is assigned a GLOBAL
   SEQUENCE NUMBER at admission (`admit()`, one counter under the
   front-door lock). Admission and delivery are deliberately two
   phases — `admit()` hands out the ticket, `deliver()` lands the
   batch in the reorder buffer — because that is the real shape of a
   wire front door (the ticket is issued when the request is accepted;
   the body lands when the producer's thread gets back around to it),
   and the gap between them is exactly where N producers interleave.
   `submit()` is the one-call form HTTP handlers use.

2. **Deterministic merge.** A single merge worker applies batches in
   SEQUENCE order, never arrival order: it waits until the next
   expected sequence number has been delivered before applying
   anything later (a reorder buffer, not a race). The applied stream
   is therefore a single well-defined total order no matter how many
   producers submitted concurrently — and replaying that order through
   synchronous single-producer `ingest()` lands on BIT-EXACT the same
   ratings (the async==sync equivalence property, now under N
   writers; `applied_log` records the order so tests and the frontend
   bench can replay it). Batches reach the engine through
   `ingest_async`, so the PR 4 packer overlap still applies downstream.

3. **Bounded-degradation shedding** (policy ``"coalesce"``). The old
   backpressure choice was all-or-nothing: block the producer, or
   drop the oldest batch on the floor. Here, when the reorder buffer
   exceeds `capacity` batches, the OLDEST contiguous batches are shed
   as batches — their traces END with the existing `pipeline.dropped`
   marker, their producers' policy-labeled drop counters tick — but
   their MATCHES are coalesced into a pending SUMMARY UPDATE that is
   applied as one batch at the shed batches' position in the total
   order. Overload costs per-batch rating granularity and freshness
   (k updates become 1, applied late), never silent data loss. The
   summary itself is staleness-bounded: once it would carry more than
   `max_staleness_matches` of backlog, its oldest whole segments are
   dropped FOR REAL and counted (`policy="staleness"` on the existing
   dropped-matches counter) — so the applied watermark can never lag
   the admitted stream by more than a computable bound, and the drop
   is a counted verdict, not an accident.

Crash-restart: `close(spill=True)` extracts the not-yet-applied state
— the summary segments plus the per-producer queued batches in
sequence order — exactly what a durable snapshot persists next to the
engine spill; `resubmit_spilled()` re-admits it in the same
deterministic order on a restarted front door. Spilled summary
segments are re-admitted as INDIVIDUAL batches (the restart undoes
pending coalescing: full granularity is restored, and the replay is
bit-exact to an uninterrupted run that never shed them).

Metrics ride the PR 7 schema unchanged: submit-path counters keep
their `producer` label (the per-producer streams are keyed by it; the
inner pipeline counts each batch under its ORIGINAL producer, not the
front door's), drops report through the existing policy-labeled
counters, and the per-producer queue-depth gauge tracks this buffer.
Everything here is host-side NumPy + stdlib threading — no jax (the
jitted work stays behind `ArenaEngine`).
"""

import bisect
import threading
import time
from collections import deque

import numpy as np

from arena import engine as engine_mod
from arena.obs import context as trace_context

POLICY_COALESCE = "coalesce"
POLICY_STALENESS = "staleness"

# Reorder-buffer capacity in BATCHES before coalescing sheds the
# oldest; small like the pipeline queue — it bounds freshness, not RAM.
DEFAULT_CAPACITY = 16

# Backlog the coalesced summary may carry before its oldest segments
# are dropped for real (matches, not batches).
DEFAULT_MAX_STALENESS_MATCHES = 100_000

# Producer label the coalesced summary update is submitted under.
SUMMARY_PRODUCER = "coalesced"

# Wait quantum: every blocking loop re-checks worker liveness.
_WAIT_S = 0.05

# Most applied-log records one /log response may carry. Replicas page:
# a bounded segment keeps one catch-up response from rendering the
# whole history into a single JSON body.
MAX_LOG_SEGMENT_RECORDS = 512

# Part of the observability contract: the sampling profiler
# (arena/obs/profile.py) maps this thread name to the "dispatcher"
# role. Rename here and the profiler's role table moves with it.
MERGE_THREAD_NAME = "arena-frontdoor-merge"


class FrontDoorError(RuntimeError):
    """The front door cannot make progress (worker dead or errored)."""


class _Ticket:
    """One admitted batch: the sequence slot plus its payload."""

    __slots__ = ("seq", "producer", "winners", "losers", "ctx")

    def __init__(self, seq, producer, winners, losers, ctx):
        self.seq = seq
        self.producer = producer
        self.winners = winners
        self.losers = losers
        self.ctx = ctx


class FrontDoor:
    """Multi-producer submit surface over one `ArenaEngine`.

    The front door owns the engine's WRITE path while it is open:
    batches reach the engine only through the merge worker, in
    sequence order. Queries/snapshots stay wherever they were
    (`ArenaServer` reads immutable views; it never contends here).
    """

    def __init__(self, engine, capacity=DEFAULT_CAPACITY,
                 max_staleness_matches=DEFAULT_MAX_STALENESS_MATCHES,
                 record_applied=False, pipeline_producer="frontdoor"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1 batch, got {capacity}")
        if max_staleness_matches < 0:
            raise ValueError(
                f"max_staleness_matches must be >= 0, got {max_staleness_matches}"
            )
        self._eng = engine
        # N producer threads and the merge worker meet under this one
        # condition; every attribute annotated below is part of that
        # shared state, and the `guarded_by` annotations make the
        # jaxlint `unguarded-shared-write` rule enforce it statically.
        self._cv = threading.Condition()
        self.capacity = capacity  # guarded_by: _cv  (set_policy retunes live)
        self.max_staleness_matches = max_staleness_matches  # guarded_by: _cv
        self.policy = POLICY_COALESCE
        self._next_seq = 0  # guarded_by: _cv  (next seq to assign at admission)
        self._next_apply = 0  # guarded_by: _cv  (next seq the merge may apply)
        self._buffer = {}  # guarded_by: _cv  (seq -> _Ticket, not applied)
        self._summary = deque()  # guarded_by: _cv  (shed segments)
        self._summary_matches = 0  # guarded_by: _cv
        self._applying = False  # guarded_by: _cv  (worker holds a popped item)
        self._closed = False  # guarded_by: _cv
        self._held = False  # guarded_by: _cv  (pause() — forced-overload hook)
        self._error = None  # guarded_by: _cv
        self.admitted_batches = 0  # guarded_by: _cv
        self.admitted_matches = 0  # guarded_by: _cv
        self.delivered_batches = 0  # guarded_by: _cv
        self.applied_batches = 0  # guarded_by: _cv
        self.applied_matches = 0  # guarded_by: _cv
        self.shed_batches = 0  # guarded_by: _cv  (coalesced, matches kept)
        self.shed_matches = 0  # guarded_by: _cv
        self.dropped_matches = 0  # guarded_by: _cv  (summary trims, really lost)
        self.summaries_applied = 0  # guarded_by: _cv
        self.max_staleness_seen = 0  # guarded_by: _cv
        self._producer_pending = {}  # guarded_by: _cv  (producer -> buffered)
        # Matches the engine had applied before this front door opened:
        # staleness_matches() measures OUR backlog, not history's.
        self._base_applied = engine.matches_applied
        # The deterministic application order, recorded for replay
        # (tests and the frontend bench's HARD equivalence gate) and —
        # since PR 18 — shipped to replicas over `GET /log`. The log
        # seq of a record is its INDEX in `applied_log` (dense,
        # gapless: exactly what strict in-order replay needs);
        # `applied_watermarks[i]` is the engine watermark after record
        # i is applied, so a replica restored from a snapshot at
        # watermark W can resume from the record boundary matching W.
        self.record_applied = record_applied
        self.applied_log = []  # guarded_by: _cv
        self.applied_watermarks = []  # guarded_by: _cv
        self._log_matches = 0  # guarded_by: _cv  (matches covered by the log)
        if engine._pipeline is None:
            engine.start_pipeline(producer=pipeline_producer)
        self._thread = threading.Thread(
            target=self._merge_loop, name=MERGE_THREAD_NAME, daemon=True
        )
        self._thread.start()

    # --- accounting ---------------------------------------------------

    def _obs(self):
        return self._eng.obs

    def staleness_matches(self):
        """Matches admitted but not yet applied (nor dropped): the
        front door's freshness lag over the engine's watermark."""
        with self._cv:
            return self._staleness_locked()

    def _staleness_locked(self):
        return (
            self.admitted_matches
            - self.dropped_matches
            - (self._eng.matches_applied - self._base_applied)
        )

    def staleness_bound(self, max_batch, producers=1):
        """The computable worst-case staleness under policy
        ``coalesce`` for batches up to `max_batch` matches: the summary
        cap, plus a full reorder buffer, plus the inner pipeline
        queue, plus one batch in flight per stage and one undelivered
        ticket per producer. The frontend bench gates the OBSERVED
        staleness against this bound."""
        pipe = self._eng._pipeline
        pipe_capacity = pipe.capacity if pipe is not None else 0
        return self.max_staleness_matches + max_batch * (
            self.capacity + pipe_capacity + producers + 2
        )

    def pending_batches(self):
        with self._cv:
            return len(self._buffer) + (1 if self._summary else 0)

    def log_segment(self, after_seq=-1, after_watermark=None,
                    limit=MAX_LOG_SEGMENT_RECORDS):
        """Page the applied log for replication: records with log seq
        > `after_seq` (or, when `after_watermark` is given, the records
        past the record boundary whose post-apply watermark equals it —
        how a replica restored from a snapshot at watermark W aligns
        its cursor without re-shipping history). Returns
        `(records, next_seq, log_len, base_watermark)` where each
        record is `(seq, kind, winners, losers, watermark)`.

        Raises ValueError when `after_watermark` does not land on a
        record boundary (a replica restored from a snapshot taken
        mid-record cannot replay strictly in sequence order and must
        fall back to an older boundary snapshot)."""
        if not self.record_applied:
            raise FrontDoorError(
                "applied-log recording is disabled on this front door; "
                "construct it with record_applied=True to ship the log"
            )
        if limit < 1:
            raise ValueError(f"limit must be >= 1 record, got {limit}")
        limit = min(int(limit), MAX_LOG_SEGMENT_RECORDS)
        with self._cv:
            log_len = len(self.applied_log)
            if after_watermark is not None:
                start = self._seq_for_watermark_locked(
                    int(after_watermark), log_len
                )
            else:
                start = int(after_seq) + 1
                if start < 0:
                    raise ValueError(
                        f"after_seq must be >= -1, got {after_seq}"
                    )
            stop = min(log_len, start + limit)
            records = [
                (
                    i,
                    self.applied_log[i][0],
                    self.applied_log[i][1],
                    self.applied_log[i][2],
                    self.applied_watermarks[i],
                )
                for i in range(start, stop)
            ]
            return records, stop, log_len, self._base_applied

    def _seq_for_watermark_locked(self, after_watermark, log_len):
        """Map a watermark onto the log cursor PAST its record
        boundary. The base watermark (engine state before the log
        began) maps to seq 0; any other watermark must equal some
        record's post-apply watermark exactly."""
        if after_watermark == self._base_applied:
            return 0
        idx = bisect.bisect_left(
            self.applied_watermarks, after_watermark, 0, log_len
        )
        if idx >= log_len or self.applied_watermarks[idx] != after_watermark:
            raise ValueError(
                f"watermark {after_watermark} is not an applied-log record "
                f"boundary (base={self._base_applied}, "
                f"records={log_len}); restore from a boundary snapshot"
            )
        return idx + 1

    def _raise_if_failed_locked(self):
        if self._error is not None:
            raise FrontDoorError(
                f"front door failed in the merge worker: {self._error!r}"
            ) from self._error

    def _check_worker_locked(self):
        self._raise_if_failed_locked()
        if (
            (self._buffer or self._summary or self._applying)
            and not self._held
            and not self._thread.is_alive()
        ):
            raise FrontDoorError(
                "merge worker is not running but batches are queued; "
                "the front door cannot drain"
            )

    def _end_dropped_trace(self, ctx):
        """The existing terminal marker: a shed batch's trace ENDS with
        `pipeline.dropped`, same as the PR 7 pipeline drop path."""
        self._obs().tracer.record_span(
            "pipeline.dropped", time.perf_counter(), 0.0, context=ctx
        )

    # --- admission (any producer thread) ------------------------------

    def admit(self, winners, losers, producer="local", tenant=None):
        """Phase 1: validate the batch and assign its global sequence
        number — the batch's slot in the total order. Raises at the
        call site on malformed input with no state change.

        A `tenant` rewrites the batch's per-tenant-local player ids
        into the engine's composite id space HERE, at admission — the
        ticket, the applied log, and the spill all carry composite ids,
        so every downstream stage (merge order, shedding, replication,
        replay) is tenant-oblivious and unchanged."""
        if not producer or not isinstance(producer, str):
            raise ValueError(
                f"producer label must be a non-empty str, got {producer!r}"
            )
        w = np.asarray(winners, np.int32)
        l = np.asarray(losers, np.int32)
        if tenant is not None:
            tenant = engine_mod._validate_tenant(self._eng.num_tenants, tenant)
            ppt = self._eng.players_per_tenant
            engine_mod._validate_matches(ppt, w, l)
            off = np.int32(tenant * ppt)
            w = w + off
            l = l + off
        else:
            engine_mod._validate_matches(self._eng.num_players, w, l)
        ctx = trace_context.current()  # the request's root (or None)
        with self._cv:
            if self._closed:
                raise FrontDoorError("front door is closed; open a new one")
            self._raise_if_failed_locked()
            seq = self._next_seq
            self._next_seq += 1
            self.admitted_batches += 1
            self.admitted_matches += int(w.shape[0])
        return _Ticket(seq, producer, w, l, ctx)

    def deliver(self, ticket):
        """Phase 2: land an admitted batch in the reorder buffer. The
        merge worker applies it once every earlier sequence number has
        been delivered (or shed) — never before."""
        obs = self._obs()
        with self._cv:
            if self._closed:
                raise FrontDoorError("front door is closed; open a new one")
            self._raise_if_failed_locked()
            self._buffer[ticket.seq] = ticket
            self.delivered_batches += 1
            pend = self._producer_pending
            pend[ticket.producer] = pend.get(ticket.producer, 0) + 1
            depth = pend[ticket.producer]
            stale = self._staleness_locked()
            self.max_staleness_seen = max(self.max_staleness_seen, stale)
            self._shed_locked()
            self._cv.notify_all()
        obs.gauge(
            "arena_pipeline_queue_depth", producer=ticket.producer
        ).set(float(depth))
        obs.gauge("arena_frontdoor_staleness_matches").set(float(stale))
        obs.event("queue_depth", depth=depth, producer=ticket.producer)
        return ticket.seq

    def submit(self, winners, losers, producer="local", tenant=None):
        """admit + deliver in one call (the HTTP handler's form).
        Returns the batch's sequence number."""
        return self.deliver(self.admit(winners, losers, producer, tenant=tenant))

    # --- the shedding policy (runs under the lock) --------------------

    def _shed_locked(self):
        """Bounded-degradation shedding. Over `capacity` buffered
        batches: coalesce the oldest contiguous batches into the
        summary (batch identity dropped — counted, trace ended — but
        matches preserved). Over `max_staleness_matches` of summary
        backlog: drop the oldest whole segments for real (counted
        under policy="staleness")."""
        obs = self._obs()
        while len(self._buffer) > self.capacity:
            item = self._buffer.pop(self._next_apply, None)
            if item is None:
                break  # head not delivered yet: nothing contiguous to shed
            self._next_apply = item.seq + 1
            n = int(item.winners.shape[0])
            self._summary.append((item.producer, item.winners, item.losers))
            self._summary_matches += n
            self.shed_batches += 1
            self.shed_matches += n
            pend = self._producer_pending
            pend[item.producer] = pend.get(item.producer, 1) - 1
            obs.counter(
                "arena_pipeline_dropped_batches_total",
                policy=POLICY_COALESCE, producer=item.producer,
            ).inc()
            obs.event("shed", policy=POLICY_COALESCE, producer=item.producer,
                      batches=1, matches=n)
            # Shed magnitude with the shed batch's OWN trace id as the
            # exemplar: the submit-delivery SLO alert resolves it into
            # the admission->shed trace of a batch that actually burned
            # budget (the trace ends with the pipeline.dropped marker
            # recorded just below).
            obs.histogram("arena_shed_batch_matches", base=1.0).record(
                float(n),
                trace_id=item.ctx.trace_id if item.ctx is not None else 0,
            )
            self._end_dropped_trace(item.ctx)
        while self._summary_matches > self.max_staleness_matches:
            producer, w, _l = self._summary.popleft()
            n = int(w.shape[0])
            self._summary_matches -= n
            self.dropped_matches += n
            obs.counter(
                "arena_pipeline_dropped_matches_total",
                policy=POLICY_STALENESS, producer=producer,
            ).inc(n)
            obs.event("drop", policy=POLICY_STALENESS, producer=producer,
                      batches=1, matches=n)

    # --- the merge worker ---------------------------------------------

    def _pop_next_locked(self):  # deterministic; mutates: _buffer, _summary, _summary_matches, _next_apply, _producer_pending
        """The deterministic merge: the pending summary (always older
        than anything still buffered) first, then the buffered batch
        at the next expected SEQUENCE number — never whichever batch
        happened to arrive first."""
        if self._summary:
            segments = list(self._summary)
            self._summary.clear()
            self._summary_matches = 0
            return ("summary", segments)
        item = self._buffer.pop(self._next_apply, None)
        if item is None:
            return None
        self._next_apply = item.seq + 1
        pend = self._producer_pending
        pend[item.producer] = pend.get(item.producer, 1) - 1
        return ("batch", item)

    def _merge_loop(self):
        while True:
            with self._cv:
                popped = None
                while True:
                    if not self._held:
                        popped = self._pop_next_locked()
                        if popped is not None:
                            break
                    if self._closed:
                        return  # closed and (contiguously) drained
                    self._cv.wait()
                self._applying = True
            try:
                self._apply(popped)
            except BaseException as exc:  # noqa: BLE001 — surface on callers
                with self._cv:
                    self._error = exc
                    self._applying = False
                    for item in self._buffer.values():
                        self._end_dropped_trace(item.ctx)
                    self._buffer.clear()
                    self._summary.clear()
                    self._summary_matches = 0
                    self._cv.notify_all()
                return
            with self._cv:
                self._applying = False
                self._cv.notify_all()

    def _apply(self, popped):  # deterministic; mutates: summaries_applied, applied_batches, applied_matches, applied_log, applied_watermarks, _log_matches; schema: applied-log-record@v1
        kind, payload = popped
        obs = self._obs()
        if kind == "summary":
            w = np.concatenate([s[1] for s in payload])
            l = np.concatenate([s[2] for s in payload])
            # The summary update: one batch, one rating step, applied
            # at the shed batches' position in the total order.
            with obs.span("frontdoor.summary_apply"):
                self._eng.ingest_async(w, l, producer=SUMMARY_PRODUCER)
            with self._cv:
                self.summaries_applied += 1
                self.applied_matches += int(w.shape[0])
                if self.record_applied:
                    self._log_matches += int(w.shape[0])
                    self.applied_watermarks.append(
                        self._base_applied + self._log_matches
                    )
                    self.applied_log.append(("summary", w, l))
        else:
            item = payload
            # Adopt the request's context: the apply span (and the
            # batch.submit/pack/dispatch spans under it) parent into
            # the submitting request's trace across threads.
            with trace_context.attach(item.ctx), obs.span("frontdoor.apply"):
                self._eng.ingest_async(
                    item.winners, item.losers, producer=item.producer
                )
            with self._cv:
                self.applied_batches += 1
                self.applied_matches += int(item.winners.shape[0])
                if self.record_applied:
                    self._log_matches += int(item.winners.shape[0])
                    self.applied_watermarks.append(
                        self._base_applied + self._log_matches
                    )
                    self.applied_log.append(("batch", item.winners, item.losers))

    # --- overload / drain / shutdown ----------------------------------

    def set_policy(self, capacity=None, max_staleness_matches=None):
        """Retune the shedding knobs on a LIVE front door — the
        operational lever (tighten under incident, loosen after; the
        frontend bench's forced-overload phase uses it). Applies
        immediately: the shed check runs once here and at every
        subsequent delivery."""
        with self._cv:
            if capacity is not None:
                if capacity < 1:
                    raise ValueError(
                        f"capacity must be >= 1 batch, got {capacity}"
                    )
                self.capacity = capacity
            if max_staleness_matches is not None:
                if max_staleness_matches < 0:
                    raise ValueError(
                        f"max_staleness_matches must be >= 0, got "
                        f"{max_staleness_matches}"
                    )
                self.max_staleness_matches = max_staleness_matches
            self._shed_locked()
            self._cv.notify_all()

    def reset_staleness_peak(self):
        """Restart the `max_staleness_seen` high-water mark (phase
        boundaries in the bench: gate each phase against its own
        configured bound)."""
        with self._cv:
            self.max_staleness_seen = self._staleness_locked()

    def pause(self):
        """Hold the merge worker (admissions continue): the forced-
        overload hook the shedding tests and the frontend bench use to
        model a stalled apply path deterministically."""
        with self._cv:
            self._held = True

    def resume(self):
        with self._cv:
            self._held = False
            self._cv.notify_all()

    def flush(self):
        """Block until every admitted batch has been delivered, merged
        in sequence order, and applied through the engine (inner
        pipeline drained too). Callers must have completed their
        admit/deliver pairs — an undelivered ticket would stall the
        merge by construction (the order gap is the point)."""
        while True:
            with self._cv:
                self._raise_if_failed_locked()
                if self._held:
                    raise FrontDoorError(
                        "front door is paused; resume() before flush()"
                    )
                if (
                    self.delivered_batches == self.admitted_batches
                    and not self._buffer
                    and not self._summary
                    and not self._applying
                ):
                    break
                self._check_worker_locked()
                self._cv.wait(_WAIT_S)
        self._eng.flush()

    def close(self, spill=False):  # schema: frontdoor-spill@v1
        """Stop the front door and join the merge worker.

        Default: drain everything contiguously deliverable, then stop
        (the engine's pipeline is flushed too). spill=True instead
        EXTRACTS the not-yet-applied state and returns it:
        ``{"summary": [(producer, winners, losers), ...],
        "queued": [(seq, producer, winners, losers), ...]}`` — summary
        segments in shed order, queued batches in sequence order, the
        exact structure `resubmit_spilled` re-admits after a restart
        (persist it next to the engine snapshot's own queue spill).
        Spilled batches are counted on the existing producer-labeled
        spill counters, never as dropped."""
        spilled = None
        obs = self._obs()
        with self._cv:
            if spill:
                spilled = {
                    "summary": [
                        (p, w, l) for p, w, l in self._summary
                    ],
                    "queued": [
                        (seq, t.producer, t.winners, t.losers)
                        for seq, t in sorted(self._buffer.items())
                    ],
                }
                per_producer = {}
                for p, w, _l in spilled["summary"]:
                    b, m = per_producer.get(p, (0, 0))
                    per_producer[p] = (b + 1, m + int(w.shape[0]))
                for _seq, p, w, _l in spilled["queued"]:
                    b, m = per_producer.get(p, (0, 0))
                    per_producer[p] = (b + 1, m + int(w.shape[0]))
                for p, (b, m) in sorted(per_producer.items()):
                    obs.counter(
                        "arena_pipeline_spilled_batches_total", producer=p
                    ).inc(b)
                    obs.counter(
                        "arena_pipeline_spilled_matches_total", producer=p
                    ).inc(m)
                    obs.event("spill", producer=p, batches=b, matches=m)
                self._buffer.clear()
                self._summary.clear()
                self._summary_matches = 0
                self._producer_pending = {}
            self._closed = True
            self._held = False
            self._cv.notify_all()
        self._thread.join(timeout=10.0)
        if not spill:
            with self._cv:
                self._raise_if_failed_locked()
            self._eng.flush()
        return spilled

    def resubmit_spilled(self, spilled):  # schema: frontdoor-spill@v1
        """Re-admit a `close(spill=True)` extraction in deterministic
        order: summary segments first (as INDIVIDUAL batches — the
        restart restores the granularity pending coalescing would have
        cost), then the queued batches in their spilled sequence
        order, each under its original producer label."""
        for producer, w, l in spilled["summary"]:
            self.submit(w, l, producer=producer)
        for _seq, producer, w, l in spilled["queued"]:
            self.submit(w, l, producer=producer)
