"""The matchmaking plane: propose pairings off ONE immutable view.

Every subsystem before this one *observes* matches; the `Matchmaker`
*schedules* them. It answers "which n pairs should play next?" from a
single `ServingView` — live ratings plus the bootstrap confidence
intervals the view carries after `refresh_intervals()` — so a proposal
batch is a pure function of (view, n, policy, tenant) and nothing else.
That purity is the whole acceptance story: the closed-loop self-play
soak (`ARENA_BENCH_MODE=matchloop`) replays bit-identically at a fixed
seed because nothing in here reads a clock, an unseeded RNG, or
mutable server state.

Policy vocabulary (`POLICIES`):

- ``fair``    — minimize pairwise win-prob skew: rank pairs by the
  match-information term ``4*p*(1-p)`` (maximal at p=0.5), where p is
  the same jitted Elo win-prob the /h2h endpoint serves.
- ``active``  — uncertainty-driven active sampling: weight fairness by
  the pair's combined CI width, so the pairs that shrink the widest
  intervals fastest rank first. Degrades to ``fair`` when intervals
  have not been refreshed yet (all widths equal).
- ``ucb``     — exploration bonus: active's score plus a UCB-style
  ``c * sqrt(log1p(total) / (n_i + n_j + 1))`` term that surfaces
  under-played players.
- ``epsilon`` — active's ranking with per-slot epsilon-random
  replacement, seeded from the view watermark.
- ``random``  — uniform distinct pairs, watermark-seeded: the control
  arm the matchloop bench measures active sampling against.

The pairwise matrices are computed through one jitted kernel over
pow2-bucketed candidate arrays (`engine.bucket_size`, v3 lint), so a
steady-state roster never recompiles; selection (triangle extraction,
stable argsort, RNG) is host-side numpy and deterministic.
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from arena import engine as engine_mod
from arena import ratings
from arena.obs import slo as slo_mod

POLICIES = ("random", "fair", "active", "epsilon", "ucb")
DEFAULT_POLICY = "active"
DEFAULT_PROPOSALS = 16
MAX_PROPOSALS = 1024
# Proposal scoring is O(candidates^2): scope to a tenant past this.
MAX_CANDIDATES = 2048
DEFAULT_EPSILON = 0.1
DEFAULT_UCB_C = 0.5
# Additive weight floor (rating points) under Boltzmann selection.
# Pure overlap weighting starves a confidently-WRONG player: once its
# misplaced interval stops overlapping its true neighbours, the
# corrective match is never scheduled and the error freezes in. The
# floor keeps every pair's selection probability bounded away from
# zero, so the closed loop keeps auditing "settled" pairs at a low
# rate — the matchloop bench measures this as active holding its lead
# over random instead of plateauing below the correlation threshold.
EXPLORATION_FLOOR = 20.0
# Domain-separates proposal RNG streams from every other consumer of
# watermark-derived seeds (e.g. bootstrap resampling).
_RNG_SALT = 0x6D617463


def pair_components(ratings_vec, widths, counts, scale):  # deterministic
    """All-pairs scoring ingredients as (B, B) matrices: win prob
    ``p[i, j] = P(i beats j)`` via the same jitted Elo expectation the
    h2h path uses, the fairness/information term ``4*p*(1-p)``, the
    combined CI width, and the UCB exploration bonus. One fused kernel
    per pow2 bucket; padded tail entries are masked out host-side by
    the triangle extraction, so their values never rank."""
    p = ratings.elo_expected(ratings_vec[:, None], ratings_vec[None, :],
                             scale=scale)
    info = 4.0 * p * (1.0 - p)
    # A never-played player's BOOTSTRAP width is zero (its rating is
    # constant across replicates) — but it is maximally uncertain, not
    # maximally certain. Blend in a prior width that decays with match
    # count so unplayed players rank as the widest intervals of all
    # instead of never being scheduled.
    eff = widths + scale / (1.0 + counts)
    width = eff[:, None] + eff[None, :]
    # CI-overlap: how ambiguous the pair's ORDER still is. Centering
    # each effective interval on its rating, two intervals overlap by
    # half the combined width minus the rating gap — zero once the
    # pair is confidently ordered. This is the active policy's target:
    # a match between still-overlapping intervals is the one that
    # shrinks ranking uncertainty fastest; a match between separated
    # intervals teaches nothing the view didn't already serve.
    gap = jnp.abs(ratings_vec[:, None] - ratings_vec[None, :])
    overlap = jnp.maximum(width / 2.0 - gap, 0.0)
    total = jnp.log1p(jnp.sum(counts))
    bonus = jnp.sqrt(total / (counts[:, None] + counts[None, :] + 1.0))
    return p, info, width, overlap, bonus


def _policy_scores(policy, info, width, overlap, bonus, ucb_c):  # deterministic
    """The pluggable ranking surface. `epsilon` ranks by `active` (its
    exploration happens at slot level in `propose_pairs`); `random`
    never reaches here."""
    if policy == "fair":
        return info
    if policy == "active":
        return overlap
    if policy == "ucb":
        return overlap * (1.0 + ucb_c * bonus)
    raise ValueError(f"policy {policy!r} has no score surface")


def _greedy_matching(flat, iu, ju, take):  # deterministic
    """Select `take` pair indices by score, matching-round constrained:
    within one round no player appears twice, and a new round opens
    only when no admissible pair is left. Without this, uncertainty
    weighting degenerates — the widest-CI player lands in every
    proposed pair and the rest of the roster starves (exactly the
    over-concentration the matchloop bench would catch as active
    losing to random). Ties and rounds are ordered by stable argsort,
    so selection is deterministic at a fixed view."""
    order = np.argsort(-flat, kind="stable")
    picks = []
    taken = np.zeros(order.size, bool)
    while len(picks) < take:
        used = set()
        progressed = False
        for k in order:
            if taken[k]:
                continue
            a, b = int(iu[k]), int(ju[k])
            if a in used or b in used:
                continue
            picks.append(int(k))
            taken[k] = True
            used.add(a)
            used.add(b)
            progressed = True
            if len(picks) == take:
                break
        if not progressed:
            break  # every remaining pair is taken
    return np.asarray(picks, np.int64)


def _pad(vec, bucket):
    out = np.zeros(bucket, np.float32)
    out[: vec.size] = vec
    return out


def propose_pairs(view, n, policy, pair_fn, tenant=None,
                  epsilon=DEFAULT_EPSILON, ucb_c=DEFAULT_UCB_C):  # deterministic
    """Propose up to `n` distinct pairings `(a, b, p_a_beats_b, score)`
    from one immutable view — tenant-local player ids when `tenant=`
    is given, composite ids otherwise. Deterministic at a fixed view:
    the RNG behind `random`/`epsilon` is seeded from
    (salt, watermark, n, policy, tenant), and ranking ties break by
    stable argsort over the pair triangle."""
    if tenant is None:
        off, num = 0, int(view.ratings.size)
    else:
        tenant = int(tenant)
        if not 0 <= tenant < view.num_tenants:
            raise ValueError(
                f"unknown tenant {tenant}: this arena serves tenants "
                f"[0, {view.num_tenants})"
            )
        num = int(view.players_per_tenant)
        off = tenant * num
    n = int(n)
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    if n > MAX_PROPOSALS:
        raise ValueError(f"n must be <= {MAX_PROPOSALS}, got {n}")
    if num > MAX_CANDIDATES:
        raise ValueError(
            f"{num} candidates exceeds the {MAX_CANDIDATES}-player "
            "proposal ceiling (scoring is all-pairs); scope the request "
            "with tenant="
        )
    if n == 0 or num < 2:
        return []

    ratings_vec = np.asarray(view.ratings[off:off + num], np.float32)
    if view.lo is None:
        # Intervals never refreshed: every CI is equally unknown, so
        # `active` degrades to `fair` instead of refusing to serve.
        widths = np.ones(num, np.float32)
    else:
        widths = np.asarray(
            view.hi[off:off + num] - view.lo[off:off + num], np.float32
        )
    counts = np.asarray(
        view.wins[off:off + num] + view.losses[off:off + num], np.float32
    )
    bucket = engine_mod.bucket_size(num)
    p, info, width, overlap, bonus = (
        np.asarray(m)[:num, :num]
        for m in pair_fn(
            _pad(ratings_vec, bucket), _pad(widths, bucket),
            _pad(counts, bucket),
        )
    )

    rng = np.random.default_rng([
        _RNG_SALT, int(view.watermark), n, POLICIES.index(policy),
        int(view.num_tenants) if tenant is None else tenant,
    ])
    iu, ju = np.triu_indices(num, k=1)
    take = min(n, int(iu.size))
    if policy == "random":
        picks = rng.choice(iu.size, size=take, replace=False)
        score = np.zeros_like(p)
    else:
        rank_by = "active" if policy == "epsilon" else policy
        score = _policy_scores(rank_by, info, width, overlap, bonus, ucb_c)
        flat = score[iu, ju]
        if rank_by == "fair":
            # Skew minimization is a deterministic objective: take the
            # fairest admissible pairs outright.
            keys = flat
        else:
            # Boltzmann exploration (Gumbel-perturbed log-weights):
            # sample pairs with probability proportional to their
            # score instead of taking the argmax. Early on every CI
            # overlaps every other, so this mixes across the whole
            # ladder like the random arm; as intervals separate, the
            # weight mass concentrates on the still-ambiguous pairs.
            # Seeded by the view watermark, so still deterministic.
            # EXPLORATION_FLOOR keeps confidently-separated pairs
            # auditable (see its definition above).
            keys = np.log(flat + EXPLORATION_FLOOR) + rng.gumbel(size=flat.size)
        picks = _greedy_matching(keys, iu, ju, take)
        if policy == "epsilon":
            explore = rng.random(take) < epsilon
            randoms = rng.choice(iu.size, size=take, replace=False)
            picks = np.where(explore, randoms, picks)
    return [
        (int(iu[k]), int(ju[k]), float(p[iu[k], ju[k]]),
         float(score[iu[k], ju[k]]))
        for k in picks
    ]


def render_match_payload(view, stale, policy, n, tenant, rows):  # pure-render(view); schema: wire-match@v1
    """The GET /match payload off one view: the standard staleness
    header fields plus the proposal rows. The payload's own
    ``watermark`` is the proposing view's — `make_response` promotes it
    into the envelope, so a client sees exactly which watermark the
    proposals were ranked at."""
    out = {
        "watermark": view.watermark,
        "matches_ingested": view.matches_ingested,
        "staleness": view.matches_ingested - view.watermark,
        "stale": stale,
        "view_seq": view.seq,
        "policy": policy,
        "n": int(n),
        "proposals": [
            {
                "a": a,
                "b": b,
                "p_a_beats_b": p_ab,
                "score": score,
            }
            for a, b, p_ab, score in rows
        ],
    }
    if tenant is not None:
        out["tenant"] = int(tenant)
    return out


class Matchmaker:  # protocol: close
    """The matchmaking plane over one `ArenaServer`: serves policy-
    ranked pairing proposals off the server's immutable views, counts
    and times every proposal through the server's one registry, and
    registers the `match-proposal-latency` SLO objective on the
    server's burn-rate engine.

    Instrumentation (all in the server's registry, so `stats()["net"]`
    and /metrics see them with zero extra plumbing):

    - ``arena_match_requests_total`` / ``arena_match_proposals_total``
    - ``arena_match_proposal_latency_seconds`` (exemplar-bearing
      histogram, the SLO objective's selector)
    - ``arena_matchmaker_present`` gauge (1 while attached, 0 after
      `close()` — the stats()/healthz presence bit)
    """

    def __init__(self, server, default_policy=DEFAULT_POLICY,
                 epsilon=DEFAULT_EPSILON, ucb_c=DEFAULT_UCB_C,
                 slo_threshold_s=slo_mod.DEFAULT_MATCH_PROPOSAL_LATENCY_S):
        if default_policy not in POLICIES:
            raise ValueError(
                f"unknown default policy {default_policy!r}: one of "
                f"{POLICIES}"
            )
        self.server = server
        self.obs = server.obs
        self.default_policy = default_policy
        self.epsilon = float(epsilon)
        self.ucb_c = float(ucb_c)
        # One jitted kernel, one compile cache: `num_compiles()` is the
        # matchloop sentinel's per-bucket recompile probe.
        self._pair_fn = jax.jit(
            partial(pair_components, scale=float(server.engine.scale))
        )
        self._c_requests = self.obs.counter("arena_match_requests_total")
        self._c_proposals = self.obs.counter("arena_match_proposals_total")
        self._h_latency = self.obs.histogram(
            "arena_match_proposal_latency_seconds"
        )
        self._g_present = self.obs.gauge("arena_matchmaker_present")
        self._g_present.set(1)
        if self.obs.slo is not None:
            try:
                self.obs.slo.add(
                    slo_mod.match_proposal_latency_slo(slo_threshold_s)
                )
            except slo_mod.SLOError:
                pass  # a second matchmaker keeps the first objective

    def num_compiles(self):
        """Compile-cache size of the pair-scoring kernel (one entry per
        pow2 bucket) — what the matchloop recompile sentinel watches."""
        return self._pair_fn._cache_size()

    def propose(self, n, policy=None, tenant=None):
        """Propose `n` pairings; returns (view, stale, policy, rows).
        Counts the request, times it into the SLO objective's
        histogram, and tags the latency exemplar with the request's
        trace."""
        policy = self.default_policy if policy is None else policy
        if policy not in POLICIES:
            raise ValueError(
                f"unknown match policy {policy!r}: one of {POLICIES}"
            )
        t0 = time.perf_counter()
        with self.obs.span("match.propose") as span:
            view, stale = self.server._serve_view()
            rows = propose_pairs(
                view, n, policy, self._pair_fn, tenant=tenant,
                epsilon=self.epsilon, ucb_c=self.ucb_c,
            )
            self._c_requests.inc()
            self._c_proposals.inc(len(rows))
            self._h_latency.record(
                time.perf_counter() - t0, trace_id=span.trace_id
            )
        return view, stale, policy, rows

    def propose_payload(self, n, policy=None, tenant=None):
        """`propose()` rendered as the wire-match@v1 payload — what the
        /match endpoint returns on both front ends."""
        view, stale, policy, rows = self.propose(
            n, policy=policy, tenant=tenant
        )
        return render_match_payload(view, stale, policy, n, tenant, rows)

    def close(self):
        """Terminal: drop the presence gauge to 0 (stats()["net"] and
        /healthz report the matchmaker gone). The jit cache and
        registry instruments need no teardown."""
        self._g_present.set(0)
