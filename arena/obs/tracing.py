"""Span tracing: monotonic-clock stage spans in a bounded ring buffer.

Where `arena/obs/metrics.py` answers "how much / how fast overall",
spans answer "where did THIS request's time go": every pipeline stage
(enqueue wait, pack, CSR merge, compaction, staging, jit dispatch,
apply) and every serving operation (view build, query, snapshot,
restore) wraps itself in `tracer.span(name)` — a context manager that
reads `time.perf_counter()` on enter and exit and records one
fixed-size row into preallocated ring arrays.

Since the trace-context layer (`arena/obs/context.py`) every span is
CAUSAL, not just named: on enter it allocates a MONOTONIC span id
(a never-reset counter, so ids survive ring wraparound) and resolves
its parent from the thread-local context — the enclosing span on this
thread, or a `TraceContext` attached from another thread (the pipeline
ships one per queue item). A span with no context becomes the ROOT of
a fresh trace id. The result is that a full cross-thread request chain
(batch submit → enqueue wait → pack → CSR merge → compaction → staging
→ jit dispatch → apply; query → view build) reconstructs as one tree
from the ring, and `trace(trace_id)` pulls exactly one request's spans
— the read that turns a p99 histogram exemplar back into a story.

Honest-timing note: spans time HOST stages — work that is complete
when `__exit__` runs (NumPy packing, lock waits, file IO, dispatch
issue). They are NOT a device-time measurement: a span around an
asynchronous jax dispatch measures dispatch issue cost, which is the
host-side quantity the pipeline overlaps (the bench's wall-clock
numbers, which DO include device time, keep their explicit
`block_until_ready` discipline — the jaxlint `timing-without-block`
rule polices that, and a corpus example shows the hand-rolled version
of this pattern being flagged while this API is not: the clock reads
live inside `_Span`, not interleaved with the caller's dispatches).

The ring is bounded and overwrite-oldest: a long soak keeps the NEWEST
`capacity` spans and counts what it dropped (`dropped` — exposed as
the `trace_dropped` counter in dumps), so tracing can stay on in
production without growing memory. Eviction can orphan a kept child
whose parent row was overwritten (parents record AFTER their children,
but a batch root records at submit-return while its dispatch span can
land much later); because span ids are monotonic and never reused,
`orphans()` distinguishes that legitimate `evicted-parent` case from a
`dangling` id that was never allocated (a bug), and the Chrome export
re-roots evicted-parent spans under an explicit synthetic
`evicted-parent` event instead of leaving dangling ids. Export is
Chrome trace-event JSON (`chrome://tracing`, Perfetto): complete "X"
events with microsecond timestamps, span/parent/trace ids in `args`,
and flow events ("s"/"f") drawing the arrows for every cross-thread
parent→child edge (producer thread → packer thread).

No jax imports (same rule as the metrics half).
"""

import json
import threading
import time
from typing import NamedTuple

from arena.obs.context import TraceContext
from arena.obs import context as trace_context


class SpanRecord(NamedTuple):
    """One completed span as read back from the ring."""

    name: str
    start: float
    duration: float
    tid: int
    span_id: int
    parent_id: int  # 0 = root
    trace_id: int


class _Span:
    """One live span: ids resolved + clock read on enter, row on exit."""

    __slots__ = ("_tracer", "_name", "_t0", "span_id", "parent_id",
                 "trace_id")

    def __init__(self, tracer, name):
        self._tracer = tracer
        self._name = name

    def __enter__(self):
        cur = trace_context.current()
        self.span_id = self._tracer._new_span_id()
        if cur is None:
            self.trace_id = self._tracer.new_trace_id()
            self.parent_id = 0
        else:
            self.trace_id = cur.trace_id
            self.parent_id = cur.span_id
        trace_context.push(TraceContext(self.trace_id, self.span_id))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        trace_context.pop()
        self._tracer.record_span(
            self._name, self._t0, t1 - self._t0,
            span_id=self.span_id, parent_id=self.parent_id,
            trace_id=self.trace_id,
        )
        return False


class Tracer:
    """Bounded ring buffer of completed spans.

    `capacity` rows are preallocated (name slots + float start/duration
    arrays + int thread/span/parent/trace ids); recording wraps around,
    overwriting the oldest row and incrementing `dropped` —
    newest-wins, fixed memory. Span and trace ids come from monotonic
    counters that NEVER reset or wrap with the ring, so a parent link
    stays meaningful after the parent's row is gone (see `orphans()`).
    All mutation happens under one small lock (a span record is a few
    list/scalar stores; contention is negligible next to the stages
    being traced).
    """

    def __init__(self, capacity=4096):
        if capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._names = [None] * capacity
        self._starts = [0.0] * capacity
        self._durs = [0.0] * capacity
        self._tids = [0] * capacity
        self._span_ids = [0] * capacity
        self._parent_ids = [0] * capacity
        self._trace_ids = [0] * capacity
        self._n = 0  # total ever recorded
        self.dropped = 0  # rows overwritten (n - capacity, floored at 0)
        self._ids_allocated = 0  # span ids handed out, monotone forever
        self._traces_allocated = 0  # trace ids handed out, monotone forever
        self._lock = threading.Lock()

    @property
    def recorded(self):
        """Total spans ever recorded (kept + dropped)."""
        return self._n

    def new_trace_id(self):
        """Allocate a fresh trace id (monotone, never reused)."""
        with self._lock:
            self._traces_allocated += 1
            return self._traces_allocated

    def _new_span_id(self):
        with self._lock:
            self._ids_allocated += 1
            return self._ids_allocated

    def span(self, name):
        """Context manager timing one named host stage; nests under the
        current thread-local context (or roots a fresh trace)."""
        return _Span(self, name)

    def record_span(self, name, start, duration, tid=None, span_id=None,
                    parent_id=None, trace_id=None, context=None):
        """Record one completed span (the non-context-manager form, for
        stages whose start/end cross function boundaries — e.g. the
        pipeline's enqueue wait — or zero-duration markers like
        `pipeline.dropped`). Identity resolution, most explicit wins:
        pass span/parent/trace ids outright (`_Span.__exit__` does), or
        a `context=TraceContext(...)` to parent into a trace captured
        elsewhere (how a dropped batch's trace gets its terminal
        marker), or nothing — the thread-local context applies, and
        with no context at all the span roots a fresh trace."""
        if tid is None:
            tid = threading.get_ident()
        if span_id is None:
            span_id = self._new_span_id()
        if trace_id is None:
            if context is None:
                context = trace_context.current()
            if context is not None:
                trace_id = context.trace_id
                parent_id = context.span_id
            else:
                trace_id = self.new_trace_id()
                parent_id = 0
        if parent_id is None:
            parent_id = 0
        with self._lock:
            i = self._n % self.capacity
            self._names[i] = name
            self._starts[i] = start
            self._durs[i] = duration
            self._tids[i] = tid
            self._span_ids[i] = span_id
            self._parent_ids[i] = parent_id
            self._trace_ids[i] = trace_id
            self._n += 1
            if self._n > self.capacity:
                self.dropped += 1

    def spans(self):
        """Kept spans as `SpanRecord`s, oldest first."""
        with self._lock:
            n = min(self._n, self.capacity)
            head = self._n % self.capacity
            order = (
                list(range(head, self.capacity)) + list(range(head))
                if self._n > self.capacity
                else list(range(n))
            )
            return [
                SpanRecord(
                    self._names[i], self._starts[i], self._durs[i],
                    self._tids[i], self._span_ids[i], self._parent_ids[i],
                    self._trace_ids[i],
                )
                for i in order
            ]

    def trace(self, trace_id):
        """Every kept span of ONE trace, oldest first — the read that
        resolves a histogram exemplar's trace id into its request."""
        return [r for r in self.spans() if r.trace_id == trace_id]

    def orphans(self):
        """Kept spans whose parent row is not in the ring, classified.

        Returns `(record, reason)` pairs; `reason` is
        ``"evicted-parent"`` when the parent id WAS allocated (its row
        was overwritten — the ring's documented information loss, and
        legitimate) or ``"dangling"`` when the id was never allocated
        at all (a wiring bug; tier-1 asserts none exist at quiescence).
        Roots (parent_id == 0) are never orphans. Meaningful at
        quiescence: a parent span still OPEN (allocated, not yet
        recorded) reads as evicted until it exits.
        """
        recs = self.spans()
        kept = {r.span_id for r in recs}
        with self._lock:
            allocated = self._ids_allocated
        out = []
        for r in recs:
            if r.parent_id and r.parent_id not in kept:
                reason = (
                    "evicted-parent"
                    if 0 < r.parent_id <= allocated
                    else "dangling"
                )
                out.append((r, reason))
        return out

    def export_chrome_trace(self):
        """Chrome trace-event list: complete ("X") events with span/
        parent/trace ids in `args`, flow events ("s"/"f") for every
        cross-thread parent→child edge, and one synthetic zero-duration
        `evicted-parent` root per trace whose real root was overwritten
        — loadable by chrome://tracing and Perfetto."""
        recs = self.spans()
        kept = {r.span_id: r for r in recs}
        with self._lock:
            allocated = self._ids_allocated
        events = []
        synthetic_rooted = set()
        for r in recs:
            args = {
                "trace_id": r.trace_id,
                "span_id": r.span_id,
                "parent_id": r.parent_id,
            }
            parent = kept.get(r.parent_id) if r.parent_id else None
            if r.parent_id and parent is None:
                reason = (
                    "evicted-parent"
                    if 0 < r.parent_id <= allocated
                    else "dangling"
                )
                args["parent"] = reason
                if reason == "evicted-parent" and r.trace_id not in synthetic_rooted:
                    synthetic_rooted.add(r.trace_id)
                    events.append({
                        "name": "evicted-parent",
                        "ph": "X",
                        "ts": round(r.start * 1e6, 3),
                        "dur": 0.0,
                        "pid": 0,
                        "tid": r.tid,
                        "args": {"trace_id": r.trace_id,
                                 "synthetic_root": True},
                    })
            events.append({
                "name": r.name,
                "ph": "X",
                "ts": round(r.start * 1e6, 3),
                "dur": round(r.duration * 1e6, 3),
                "pid": 0,
                "tid": r.tid,
                "args": args,
            })
            if parent is not None and parent.tid != r.tid:
                # Flow arrow: the producer-thread parent hands work to
                # this thread (submit → pack, submit → dispatch).
                events.append({
                    "name": "trace", "cat": "trace", "ph": "s",
                    "id": r.span_id,
                    "ts": round(parent.start * 1e6, 3),
                    "pid": 0, "tid": parent.tid,
                })
                events.append({
                    "name": "trace", "cat": "trace", "ph": "f", "bp": "e",
                    "id": r.span_id,
                    "ts": round(r.start * 1e6, 3),
                    "pid": 0, "tid": r.tid,
                })
        return events

    def export_chrome_trace_json(self):
        return json.dumps({"traceEvents": self.export_chrome_trace()})


class _NullSpan:
    """Singleton no-op context manager (zero allocation per span).

    Carries the id attributes of a real `_Span` as constant zeros so
    instrumentation code can read `span.trace_id` unconditionally
    (a zero trace id means "no trace" everywhere — histograms skip
    exemplars for it)."""

    __slots__ = ()

    span_id = 0
    parent_id = 0
    trace_id = 0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


class NullTracer:
    """No-op twin of `Tracer`: `span()` hands back one shared no-op
    context manager, nothing is ever recorded or allocated."""

    capacity = 0
    dropped = 0
    recorded = 0
    _SPAN = _NullSpan()

    def span(self, name):
        return self._SPAN

    def new_trace_id(self):
        return 0

    def record_span(self, name, start, duration, tid=None, span_id=None,
                    parent_id=None, trace_id=None, context=None):
        return None

    def spans(self):
        return []

    def trace(self, trace_id):
        return []

    def orphans(self):
        return []

    def export_chrome_trace(self):
        return []

    def export_chrome_trace_json(self):
        return '{"traceEvents": []}'
