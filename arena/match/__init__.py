"""arena.match: the matchmaking plane (see `arena.match.matchmaker`).

Proposes policy-ranked pairings off one immutable `ServingView`; served
over the wire as `GET /match?n=&tenant=&policy=` when a `Matchmaker` is
attached to `ArenaHTTPServer`, and exercised end to end by the
closed-loop self-play soak (`ARENA_BENCH_MODE=matchloop`).
"""

from arena.match.matchmaker import (
    DEFAULT_EPSILON,
    DEFAULT_POLICY,
    DEFAULT_PROPOSALS,
    DEFAULT_UCB_C,
    EXPLORATION_FLOOR,
    MAX_CANDIDATES,
    MAX_PROPOSALS,
    POLICIES,
    Matchmaker,
    pair_components,
    propose_pairs,
    render_match_payload,
)

__all__ = [
    "DEFAULT_EPSILON",
    "DEFAULT_POLICY",
    "DEFAULT_PROPOSALS",
    "DEFAULT_UCB_C",
    "EXPLORATION_FLOOR",
    "MAX_CANDIDATES",
    "MAX_PROPOSALS",
    "POLICIES",
    "Matchmaker",
    "pair_components",
    "propose_pairs",
    "render_match_payload",
]
