"""jaxlint corpus: host-synchronizing calls inside a jitted body.

`print`, `float()`, `np.asarray`, and `.item()` each force a device
round-trip (or crash under tracing). Rule: host-sync-in-jit."""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def traced_loss(x):
    total = jnp.sum(x)
    print("loss so far", float(total))
    host_copy = np.asarray(x)
    return total + host_copy.item()
