"""jaxlint corpus: a hand-rolled "span" timing async dispatch inline.

The tempting DIY version of `arena.obs.tracing`: read the clock, issue
the jitted work, read the clock again, call the difference a "span".
JAX dispatch is asynchronous, so the second read lands while the
device is still computing — the recorded span measures dispatch issue,
not the work, and the trace lies. Rule: timing-without-block.

The real tracing API does not trip this rule — its clock reads live
inside `_Span.__enter__`/`__exit__`, never interleaved with the
caller's dispatches, and its spans are documented as HOST-stage
timings (the honest quantity). `tests/test_analysis_lint.py` pins both
halves: this file fires the rule; code using `obs.span(...)` does not.
"""

import time

import jax.numpy as jnp

_SPANS = []


def record_epoch_span(x):
    start = time.perf_counter()
    y = jnp.dot(x, x)
    _SPANS.append(("epoch", start, time.perf_counter() - start))
    return y
