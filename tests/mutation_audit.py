"""Mutation audit: prove the test suite polices the honesty machinery.

Every claim this repo makes — "an unreadable sidecar can never read as
drift", "a gate crash can never read as rc 1", "bench can never report
a half-scanned tree as empty" — is enforced only by tests/. This script
checks that enforcement is real: it copies the runtime surface to a
temp directory, introduces one targeted bug at a time (each the exact
failure its property forbids), runs the suite against the mutated copy,
and requires every mutant to be KILLED (suite goes red). A SURVIVED
mutant means a documented honesty property is no longer test-enforced —
the one way this repo can silently rot.

Not a test itself (deliberately not named test_*): every mutant costs
a full pytest subprocess run (~6-7s on this 1-CPU image), plus one
clean-baseline run — minutes of wall-clock across the MUTATIONS list,
too slow for the regular suite the SKILL.md says to keep fast. Run on
demand:

    python tests/mutation_audit.py            # rc 0 iff all mutants killed

What keeps THIS file from rotting instead: tests/test_mutation_audit.py
(in the regular suite, milliseconds) asserts every mutation's `old`
pattern still matches the live source, so a refactor that invalidates a
mutation turns the suite red immediately rather than letting the audit
degrade into a no-op.

The audit run excludes test_mutation_audit.py from the mutated copy —
by construction it fails under ANY source mutation (the pattern no
longer matches), which would "kill" every mutant for free and void the
audit. Exclusion is what makes a KILLED verdict meaningful.

Output: one JSON summary line on stdout (per-mutant progress on
stderr). Exit codes follow the repo's crash-vs-verdict discipline (a
crash must never collide with a measured verdict, same as the gate's
rc 4): 0 = every mutant killed; 1 = at least one SURVIVED (a measured
verdict); 2 = the unmutated copy's suite was already red (nothing
measurable); 3 = the audit itself crashed (timeout, copy failure —
JSON error line, no verdict either way).
"""

import json
import pathlib
import shutil
import subprocess
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
import bench  # the LIVE repo's error-detail formatting, shared repo-wide

# The runtime surface plus everything the suite needs to run. .git is
# deliberately not copied: the hygiene tests build their own temp git
# repos, and the copy must not look like a work tree. arena/ and
# pytest.ini ride along because the copied suite imports the arena
# package and the registered `slow` marker.
COPIED = (
    "bench.py",
    "verify_reference.py",
    "reference_fingerprint.json",
    "BASELINE.json",
    "BENCH_BASELINE.json",
    "PAPERS.md",
    "SNIPPETS.md",
    # The jaxlint selfcheck greps README's rule table against the live
    # registry (doc/code drift tripwire), so the doc rides along.
    "README.md",
    "pytest.ini",
    "arena",
    "tests",
)

# Each mutation is the EXACT misbehavior a documented property forbids,
# expressed as a unique literal substring of the live source (uniqueness
# and presence are enforced by tests/test_mutation_audit.py). Fields:
# (name, relative file, old, new, the property a survivor would break).
MUTATIONS = (
    (
        "unreadable-sidecar-reads-as-absent",
        "verify_reference.py",
        '        return SIDECAR_UNREADABLE, bench.exc_detail(exc)\n    try:',
        '        return SIDECAR_ABSENT, None\n    try:',
        "a read hiccup must classify as transient, never as the content fact 'absent'",
    ),
    (
        "unreadable-sidecar-counts-as-genuine-drift",
        "verify_reference.py",
        'or observed[d["fact"]] == SIDECAR_UNREADABLE',
        'or False',
        "an unreadable sidecar must never escalate rc 3 to rc 1 (false drift)",
    ),
    (
        "transient-exits-as-drift",
        "verify_reference.py",
        '        exit_code = EXIT_TRANSIENT',
        '        exit_code = EXIT_DRIFT',
        "rc 3 and rc 1 must be distinct for exit-code-only consumers",
    ),
    (
        "half-scanned-tree-reports-empty",
        "bench.py",
        '    except OSError:\n        return {\n            "metric": "reference_scan_error",\n            "value": -1,',
        '    except OSError:\n        return {\n            "metric": "non_graftable_reference_is_empty",\n            "value": 0,',
        "a mid-walk OSError must never report as an authoritative empty tree",
    ),
    (
        "manifest-loses-file-hashes",
        "verify_reference.py",
        'return {"path": rel, "type": "file", "size": fst.st_size, "sha256": digest}',
        'return {"path": rel, "type": "file", "size": fst.st_size, "sha256": None}',
        "the remount manifest must carry per-file sha256 (SURVEY rewrite evidence)",
    ),
    (
        "hygiene-check-always-clean",
        "verify_reference.py",
        '    return sorted(\n        {entry[3:] for entry in proc.stdout.split("\\0") if len(entry) > 3}\n    )',
        '    return []',
        "uncommitted round artifacts must be reported, not silently dropped",
    ),
    (
        "gate-crash-exits-1",
        "verify_reference.py",
        '        return EXIT_INTERNAL_ERROR',
        '        return 1',
        "a gate crash (rc 4) must never collide with genuine drift (rc 1)",
    ),
    (
        "fingerprint-accepts-non-int-count",
        "verify_reference.py",
        '            not isinstance(fingerprint_count, int)\n'
        '            or isinstance(fingerprint_count, bool)\n'
        '            or fingerprint_count < 0',
        '            False',
        "a corrupt fingerprint count must exit rc 2, not validate future transients",
    ),
    (
        "match-note-endorses-stale-emptiness",
        "verify_reference.py",
        '        if count == 0:',
        '        if isinstance(count, int):',
        "an rc-0 match on a re-pinned NON-EMPTY tree must not claim emptiness",
    ),
    (
        "bench-breaks-one-line-contract",
        "bench.py",
        '        print(line)\n        sys.stdout.flush()\n        return 0',
        '        print(line)\n        print("extra")\n        sys.stdout.flush()\n        return 0',
        "bench must print exactly one JSON line (driver contract)",
    ),
    (
        "bench-buffered-write-failure-escapes-guard",
        "bench.py",
        '        print(line)\n        sys.stdout.flush()\n        return 0',
        '        print(line)\n        return 0',
        "with a block-buffered stdout a failed write only surfaces at flush; "
        "the flush must happen inside bench's own guard (rc 1), not at "
        "interpreter exit (CPython's undocumented exit 120)",
    ),
    (
        "bench-print-failure-reads-as-success",
        "bench.py",
        '        return 1  # no JSON line was possible',
        '        return 0  # no JSON line was possible',
        "when stdout is unwritable and no JSON line can exist, bench must not "
        "exit 0 — an empty rc-0 output would be a fake success",
    ),
    (
        "import-crash-exits-1",
        "verify_reference.py",
        '    sys.exit(EXIT_INTERNAL_ERROR)',
        '    sys.exit(1)',
        "a bench-import failure at gate load must exit rc 4, never collide with drift's rc 1",
    ),
    (
        "mount-type-swap-reads-as-transient",
        "verify_reference.py",
        '        mount_state, mount_detail = observe_mount_type(reference)\n'
        '        if mount_state == MOUNT_NOT_A_DIR:',
        '        mount_state, mount_detail = observe_mount_type(reference)\n'
        '        if False:',
        "a file/FIFO/symlink-loop AT the mount path is a persistent state change "
        "(rc 1, type named), never a transient 're-run and it'll clear' (rc 3)",
    ),
    (
        "manifest-escapes-hygiene-check",
        "verify_reference.py",
        '    "SNIPPETS.md",\n    MANIFEST_NAME,\n)',
        '    "SNIPPETS.md",\n)',
        "the gate-written remount manifest must be covered by the uncommitted-"
        "artifact check — remount day is the hygiene backstop's highest-stakes day",
    ),
    (
        "vcs-warning-dropped-on-write-failure",
        "verify_reference.py",
        '        else:\n'
        '            manifest_shape = classify_manifest_shape(entries)\n'
        '            try:\n'
        '                manifest = write_manifest(\n'
        '                    reference, repo, entries, manifest_shape\n'
        '                )',
        '        else:\n'
        '            try:\n'
        '                manifest = write_manifest(\n'
        '                    reference, repo, entries\n'
        '                )\n'
        '                manifest_shape = classify_manifest_shape(entries)',
        "the VCS-only materialize warning is evidence from the walk and must "
        "survive a failed manifest write (read-only repo dir / full disk)",
    ),
    (
        "mount-absence-escalates-to-drift",
        "verify_reference.py",
        '    except FileNotFoundError:\n        return MOUNT_ABSENT, None',
        '    except FileNotFoundError:\n        return MOUNT_NOT_A_DIR, "path absent"',
        "an absent mount (driver not ready yet) must stay transient rc 3, never "
        "escalate to wrong-type drift rc 1",
    ),
    (
        "bare-git-tree-reads-as-working-source",
        "verify_reference.py",
        '    top = {entry["path"].split("/", 1)[0] for entry in entries}',
        '    top = set()',
        "a VCS-metadata-only remount (bare/hidden .git) must be classified and "
        "flagged for materialization, never surveyed as a plain source tree",
    ),
    (
        "bench-crash-masquerades-as-empty",
        "bench.py",
        '            "metric": "bench_internal_error",\n            "value": -1,',
        '            "metric": "non_graftable_reference_is_empty",\n            "value": 0,',
        "a bench crash must degrade to a visible error metric, never an authoritative empty-tree report",
    ),
    (
        "lint-host-sync-rule-blinded",
        "arena/analysis/jaxlint.py",
        '_HOST_SYNC_CALLS = frozenset({"float", "int", "bool", "print", "np.asarray", "np.array", "numpy.asarray", "numpy.array"})',
        "_HOST_SYNC_CALLS = frozenset()",
        "the host-sync lint rule must flag device round-trips inside jitted "
        "bodies; an emptied call set voids the hot-path protection while the "
        "linter still reports success — the corpus test must catch it",
    ),
    (
        "ingest-drops-the-delta-tail",
        "arena/ingest.py",
        "            self._keys, self._pos = _gallop_merge(\n"
        "                self._keys, self._pos, tail_k[order], tail_p[order]\n"
        "            )",
        "            self._keys, self._pos = self._keys, self._pos",
        "compaction must MERGE the delta tail into the main runs, never "
        "silently discard it — killed by "
        "test_galloping_merge_preserves_every_entry (and every ingest "
        "equivalence property)",
    ),
    (
        "ingest-compaction-threshold-inverted",
        "arena/ingest.py",
        "        if self._tail_entries > self._compact_limit():",
        "        if self._tail_entries < self._compact_limit():",
        "the compaction limit gates WHEN the galloping merge runs: "
        "inverted, every small add pays a merge (or the tail never folds) — "
        "killed by test_compaction_respects_threshold",
    ),
    (
        "chunked-bt-padded-back-to-one-bucket",
        "arena/ingest.py",
        "    num_chunks = -(-total // chunk_entries)",
        "    chunk_entries = bucket_size(total)\n    num_chunks = 1",
        "the chunked BT layout exists to cap the peak bucket at one chunk; "
        "padding everything back into one pow2 bucket reintroduces the 2x "
        "memory cliff — killed by "
        "test_chunk_layout_peak_bucket_strictly_smaller_than_pow2",
    ),
    (
        "ingest-size-ratio-check-inverted",
        "arena/ingest.py",
        "        return max(self.compact_threshold, self._keys.size // self.size_ratio)",
        "        return min(self.compact_threshold, self._keys.size // self.size_ratio)",
        "the LSM size-ratio policy must let the tolerated tail GROW with the "
        "main runs (amortized O(size_ratio) merge cost per entry); min() "
        "collapses the limit back to the fixed floor, re-introducing one "
        "O(main) merge per batch as the base grows — killed by "
        "test_size_ratio_policy_scales_with_base",
    ),
    (
        "pipeline-packer-thread-never-started",
        "arena/pipeline.py",
        "        self._thread.start()",
        "        pass  # packer thread intentionally not started",
        "the overlapped path's packing must actually run on the background "
        "thread; never starting it would make every ingest_async silently "
        "queue forever — the liveness check turns that into PipelineError at "
        "the next flush, killed by test_async_matches_sync_bit_exact (and "
        "every other pipeline lifecycle test)",
    ),
    (
        "pipeline-equivalence-gate-skipped",
        "arena/bench_arena.py",
        "    if not max_async_diff < tol:\n"
        "        raise EquivalenceError(max_async_diff, tol)\n"
        "    max_cold_diff = float(np.abs(r_async - r_cold).max())\n"
        "    if not max_cold_diff < tol:\n"
        "        raise EquivalenceError(max_cold_diff, tol)",
        "    if False:\n"
        "        raise EquivalenceError(max_async_diff, tol)\n"
        "    max_cold_diff = float(np.abs(r_async - r_cold).max())\n"
        "    if False:\n"
        "        raise EquivalenceError(max_cold_diff, tol)",
        "the bench's hard equivalence gate must cover the ASYNC path — BOTH "
        "comparisons (async vs sync, async vs cold replay); with the whole "
        "gate skipped, a diverging pipeline could still report an overlap "
        "speedup — killed by "
        "test_pipeline_bench_equivalence_gate_extends_to_async_path (tol 0 "
        "must exit rc 2, never rc 0). An earlier single-comparison version "
        "of this mutant SURVIVED the audit (the cold gate masked the async "
        "gate at tol 0) — the pattern deliberately covers the full block",
    ),
    (
        "serving-restore-drops-the-delta-tail",
        "arena/ingest.py",
        "        if run_lengths.size:\n"
        "            splits = np.cumsum(run_lengths)[:-1]\n"
        "            csr._tail_keys = list(np.split(tail_keys, splits))\n"
        "            csr._tail_pos = list(np.split(tail_pos, splits))\n"
        "        csr._tail_entries = tail_keys.size",
        "        csr._tail_entries = 0",
        "a restored store must carry the delta tail's grouping runs; "
        "dropping them is a SILENT partial restore (ratings and match log "
        "look intact, every un-compacted entry's grouping is gone) — killed "
        "by test_crash_restart_replay_is_bit_exact (restored tail_entries "
        "> 0 and grouping covers every interleaved entry)",
    ),
    (
        "serving-staleness-watermark-never-refreshed",
        "arena/serving.py",
        "        if view is None or self._staleness(view) > self.max_staleness_matches:\n"
        "            view = self.refresh_view()",
        "        if view is None:\n"
        "            view = self.refresh_view()",
        "the staleness policy must refresh a view once the stream moves past "
        "max_staleness_matches; frozen at its first watermark the server "
        "silently serves arbitrarily stale ratings forever — killed by "
        "test_view_watermark_advances_past_staleness_bound",
    ),
    (
        "serving-snapshot-version-check-skipped",
        "arena/serving.py",
        '    found_version = manifest.get("version")\n'
        "    if found_version != SNAPSHOT_VERSION:",
        '    found_version = manifest.get("version")\n'
        "    if False:",
        "the snapshot loader must reject a version it does not speak with "
        "the distinct SnapshotError naming expected vs found, never "
        "restore a format it cannot be sure it parses correctly — killed by "
        "test_restore_rejects_mismatched_manifest_version",
    ),
    (
        "obs-histogram-wrong-bucket",
        "arena/obs/metrics.py",
        '        return int(np.searchsorted(self.bounds, value, side="left"))',
        '        return int(np.searchsorted(self.bounds, value, side="right"))',
        "the log2 histogram must place a value exactly ON a bucket's upper "
        "bound INTO that bucket (le semantics); side=\"right\" shifts every "
        "boundary value one bucket up, silently skewing every p50/p99 the "
        "system reports — killed by "
        "test_histogram_bucket_boundary_values_land_exactly",
    ),
    (
        "serving-stats-drops-sentinel-counters",
        "arena/serving.py",
        "        self._observe_sanitizers()\n        reg = self.obs.registry",
        "        pass\n        reg = self.obs.registry",
        "stats() must absorb the sentinel/donation-guard counters into the "
        "registry before reporting; dropping the absorption makes "
        "recompile_events read 0 while the engine recompiles — the exact "
        "silent rot the soak gate stands on — killed by "
        "test_stats_reports_absorbed_sentinel_counters_from_registry",
    ),
    (
        "soak-gate-skipped",
        "arena/bench_arena.py",
        "    if not max_diff < tol:\n"
        "        raise EquivalenceError(max_diff, tol)\n"
        "    if torn or not max_mass_dev[0] < tol:\n"
        "        raise EquivalenceError(float(\"inf\"), tol)\n"
        "    if soak_recompiles != 0:",
        "    if False:\n"
        "        raise EquivalenceError(max_diff, tol)\n"
        "    if False:\n"
        "        raise EquivalenceError(float(\"inf\"), tol)\n"
        "    if False:",
        "the soak bench's HARD gates (sync-replay equivalence, torn views, "
        "zero recompile events) must all hold before any p99 is reported; "
        "with the whole block skipped a diverging or recompiling soak would "
        "still exit rc 0 — killed by test_soak_bench_gate_is_hard (tol 0 "
        "must exit rc 2, never rc 0); the full block is covered so no "
        "single surviving condition can mask another (the lesson the "
        "pipeline gate mutant already taught)",
    ),
    (
        "obs-exemplar-recorded-into-wrong-bucket",
        "arena/obs/metrics.py",
        "            if trace_id:\n"
        "                self._ex_trace[idx] = trace_id\n"
        "                self._ex_value[idx] = value",
        "            if trace_id:\n"
        "                self._ex_trace[0] = trace_id\n"
        "                self._ex_value[0] = value",
        "a latency exemplar must land in the bucket its value belongs to "
        "(the same le-semantics index the count uses); pinned to bucket 0, "
        "'show me the trace behind the p99 bucket' silently answers with an "
        "arbitrary fast request's trace — killed by "
        "test_exemplar_lands_in_recorded_values_bucket",
    ),
    (
        "obs-debug-bundle-omits-registry-dump",
        "arena/obs/debug.py",
        '    (tmp / "metrics.json").write_text(\n'
        "        json.dumps(obs.registry.dump(), indent=1, sort_keys=True)\n"
        "    )",
        "    pass",
        "the flight recorder's bundle must carry the full registry dump — "
        "a postmortem without the counters/histograms that fired the gate "
        "is a bundle-shaped empty box — killed by "
        "test_debug_bundle_contains_registry_dump",
    ),
    (
        "obs-watchdog-tolerance-inverted",
        "arena/obs/regress.py",
        '    if direction == "higher":\n'
        "        return value < base * (1.0 - tol)\n"
        "    return value > base * (1.0 + tol)",
        '    if direction == "higher":\n'
        "        return value > base * (1.0 + tol)\n"
        "    return value < base * (1.0 - tol)",
        "the watchdog's tolerance comparison must flag the BAD side of the "
        "band: inverted, a 20% throughput regression exits rc 0 while every "
        "improvement exits rc 1 — the bench trajectory gate becomes "
        "actively misleading — killed by "
        "test_watchdog_flags_regressions_not_improvements",
    ),
    (
        "net-sequence-order-ignored-at-merge",
        "arena/net/frontdoor.py",
        "        item = self._buffer.pop(self._next_apply, None)\n"
        "        if item is None:\n"
        "            return None\n"
        "        self._next_apply = item.seq + 1",
        "        if not self._buffer:\n"
        "            return None\n"
        "        item = self._buffer.pop(next(iter(self._buffer)))\n"
        "        self._next_apply = item.seq + 1",
        "the front door's merge must apply batches in SEQUENCE order (the "
        "admission-assigned total order), never in the order batch bodies "
        "happened to arrive in the buffer — arrival order under N producers "
        "is a race, not a replayable stream, and breaks the async==sync "
        "bit-exact equivalence property — killed by "
        "test_merge_applies_sequence_order_not_arrival_order",
    ),
    (
        "net-shed-coalesce-drops-matches-silently",
        "arena/net/frontdoor.py",
        '            with obs.span("frontdoor.summary_apply"):\n'
        "                self._eng.ingest_async(w, l, producer=SUMMARY_PRODUCER)",
        '            with obs.span("frontdoor.summary_apply"):\n'
        "                pass",
        "bounded-degradation shedding PRESERVES the shed batches' matches as "
        "one summary update; omitting the summary apply turns coalescing "
        "into silent data loss (exactly the all-or-nothing drop the policy "
        "replaces) while every counter still reads 'coalesced' — killed by "
        "test_shed_batches_coalesce_into_summary_update (engine match count "
        "and the bit-exact replay both break)",
    ),
    (
        "net-wire-response-omits-staleness-watermark",
        "arena/net/protocol.py",
        '    out["watermark"] = watermark\n    out["trace_id"] = trace_id',
        '    out["trace_id"] = trace_id',
        "every wire response must carry the staleness watermark next to the "
        "request's trace id (ROADMAP item 1's response contract); dropping "
        "it from the envelope leaves clients unable to tell fresh answers "
        "from stale ones — killed by "
        "test_every_wire_response_carries_watermark_and_trace_id",
    ),
    (
        "lint-donation-poisoning-dropped",
        "arena/analysis/jaxlint.py",
        "                            if target_name:\n"
        "                                poisoned[target_name] = fname",
        "                            if target_name:\n"
        "                                pass",
        "the use-after-donate rule must track buffers through donating "
        "calls; dropping the poisoning step makes every reuse-after-donate "
        "invisible — the corpus test must catch it",
    ),
    (
        "lint-symbol-table-skips-imports",
        "arena/analysis/project.py",
        "            for alias in node.names:\n"
        "                imports[alias.asname or alias.name] = (module, alias.name)",
        "            for alias in node.names:\n"
        "                continue  # from-imports deliberately skipped",
        "the v2 symbol table's import half IS the cross-module capability: "
        "with `from x import y` bindings dropped, a mesh defined in module A "
        "can never resolve from module B and sharding-spec-arity silently "
        "reverts to the v1 per-file blindness ROADMAP item 3 names — killed "
        "by test_symbol_table_resolves_from_imports (and the cross-module "
        "mesh fixture tests)",
    ),
    (
        "lint-guarded-write-check-ignores-with-blocks",
        "arena/analysis/project.py",
        "                        inner.append(lock_id)\n"
        "                        acquired.add(lock_id)",
        "                        acquired.add(lock_id)",
        "the held-lock scanner must treat `with self._lock:` bodies as held "
        "regions; without the push every correctly-locked write in the four "
        "annotated production modules reads as unguarded and the clean-tree "
        "gate goes red — killed by "
        "test_guarded_write_inside_with_lock_block_is_clean (and "
        "test_full_tree_lints_clean_with_concurrency_rules_active)",
    ),
    (
        "lint-lock-order-graph-edges-dropped",
        "arena/analysis/project.py",
        "                        for outer in inner:\n"
        "                            edges.append((outer, lock_id, item.context_expr))",
        "                        for outer in inner:\n"
        "                            pass  # nesting edges deliberately dropped",
        "the lock-order graph's nesting edges are the inversion rule's raw "
        "material; with them dropped, opposite lock orders across modules "
        "(the deadlock class) lint clean — killed by "
        "test_lock_order_inversion_detected_across_modules (and the "
        "bad_lock_order corpus contract)",
    ),
    (
        "lattice-join-returns-bottom",
        "arena/analysis/absint.py",
        "    if a.rank < b.rank:\n"
        "        return b\n"
        "    if b.rank < a.rank:\n"
        "        return a\n"
        "    if a == b:\n"
        "        return a",
        "    if a.rank < b.rank:\n"
        "        return SHAPE_BOTTOM\n"
        "    if b.rank < a.rank:\n"
        "        return SHAPE_BOTTOM\n"
        "    if a == b:\n"
        "        return SHAPE_BOTTOM",
        "the abstract shape lattice's join is the substrate every v3 rule "
        "rides: collapsed to bottom, a dynamic size joined across a branch "
        "or a loop silently reads as 'no information' and the "
        "unbucketed-shape rule goes blind while the linter still reports "
        "success — killed by test_shape_join_commutative_idempotent "
        "(join(x, x) == x fails for any non-bottom x)",
    ),
    (
        "bucketing-op-not-recognized",
        "arena/analysis/absint.py",
        'BUCKETING_TAILS = frozenset({\n'
        '    "bucket_size", "next_pow2", "_pow2_ceil", "pack_batch", "pack_epoch",\n'
        '    "chunk_layout", "stage", "pad",\n'
        '})',
        'BUCKETING_TAILS = frozenset()',
        "the recognized bucketing ops are the ONLY calls that launder a "
        "raw-length size back to a safe shape; un-recognizing them turns "
        "every real bucket_size/pack_batch call site into a finding (or, "
        "equivalently, stops the rule from distinguishing bucketed flows "
        "from raw ones) — killed by "
        "test_pow2_bucketing_ops_are_recognized_sanitizers (the "
        "bucket_size fixture must lint CLEAN)",
    ),
    (
        "taint-sanitizer-check-skipped",
        "arena/analysis/absint.py",
        'TAINT_SANITIZER_TAILS = frozenset({\n'
        '    "parse_submit_body", "parse_path", "_query_int", "_validate_matches",\n'
        '    "_validate_tenant", "pack_batch", "pack_epoch",\n'
        '})',
        'TAINT_SANITIZER_TAILS = frozenset()',
        "the taint rule's whole meaning is 'sanitized on every path': with "
        "sanitizer recognition skipped, the documented safe flows (request "
        "body through parse_submit_body into the front door, "
        "_validate_matches before store.add) read as violations — killed "
        "by test_protocol_validators_clear_taint (both sanctioned flows "
        "must lint CLEAN)",
    ),
    (
        "lint-json-format-omits-rule-name",
        "arena/analysis/jaxlint.py",
        '        "rule": finding.rule,\n        "path": finding.path,',
        '        "path": finding.path,',
        "the --format=json contract is one finding per line with the rule "
        "NAME in the object — a consumer (CI, the perf watchdog) that cannot "
        "tell which rule fired cannot gate on it — killed by "
        "test_json_format_lines_carry_rule (and the CLI subprocess schema "
        "check)",
    ),
    (
        "window-ring-never-rotates",
        "arena/obs/windows.py",
        "            self._head = (self._head + 1) % len(self._ring)",
        "            self._head = (self._head + 0) % len(self._ring)",
        "with the head frozen, every rotation overwrites the SAME slot, so "
        "ring[head] holds the NEWEST boundary instead of the oldest and "
        "every 'full window' silently collapses to just the last interval "
        "— rolling rates and windowed p99s quietly under-report while all "
        "reads still succeed — killed by "
        "test_window_merges_counts_across_ring_intervals (counts recorded "
        "across two rotations must BOTH be in the full-window delta)",
    ),
    (
        "burn-rate-alert-threshold-inverted",
        "arena/obs/slo.py",
        "                firing = (\n"
        "                    burn_fast >= slo.burn_threshold\n"
        "                    and burn_slow >= slo.burn_threshold\n"
        "                )",
        "                firing = (\n"
        "                    burn_fast <= slo.burn_threshold\n"
        "                    and burn_slow <= slo.burn_threshold\n"
        "                )",
        "an inverted comparison pages on HEALTH and sleeps through "
        "incidents — the worst possible alerting engine, and one every "
        "steady-state read would mistake for a working one — killed by "
        "test_burn_rate_alert_fires_only_above_threshold (silent at 0.1x "
        "burn AND firing at 500x burn; the frontend bench hard-gates the "
        "same both ways over real HTTP)",
    ),
    (
        "debug-endpoint-omits-envelope",
        "arena/net/server.py",
        '    if endpoint == "debug_window":\n'
        "        return 200, wire.obs.windows.read()",
        '    if endpoint == "debug_window":\n'
        "        return 200, None",
        "a None payload routes into the /stats Prometheus-text path: the "
        "response drops the JSON envelope (watermark + trace_id) and the "
        "ops plane silently stops honoring the wire contract every other "
        "endpoint carries — killed by "
        "test_debug_endpoints_serve_the_standard_envelope (the /debug/"
        "window body must be a JSON dict wearing the pair)",
    ),
    (
        "exception-edge-dropped-from-cfg",
        "arena/analysis/cfg.py",
        "            self.cfg._edge(idx, frame.exc, EDGE_EXC)",
        "            pass  # exception edges deliberately dropped",
        "_simple() is the single point every raise-capable statement "
        "passes through; with its exception edge dropped, the whole v4 "
        "analyzer sees only the happy path — the happy-path-only release "
        "shape lints clean and missing-finally-for-paired-call goes mute "
        "— killed by test_missing_finally_requires_the_exception_edge "
        "(and the CFG totality sweep "
        "test_every_raise_capable_statement_has_an_exception_successor)",
    ),
    (
        "lifecycle-terminal-state-not-tracked",
        "arena/analysis/lifecycle.py",
        '            elif tag == "close":\n'
        "                key = ev[1]\n"
        "                closed.add(key)",
        '            elif tag == "close":\n'
        "                key = ev[1]",
        "the typestate transfer must RECORD the terminal transition, not "
        "just discharge open obligations: with the closed-set update "
        "dropped, a method call after close()/shutdown() on a later "
        "statement reads as a live object and use-after-close never "
        "fires — killed by "
        "test_use_after_close_fires_and_terminal_state_is_tracked (and "
        "the bad_use_after_close corpus contract)",
    ),
    (
        "release-in-helper-not-credited",
        "arena/analysis/lifecycle.py",
        "        for key in sorted(self._helper_released_keys(call, fname)):\n"
        '            events.append(("helper-rel", key))',
        "        for key in sorted(self._helper_released_keys(call, fname)):\n"
        "            pass  # helper releases deliberately not credited",
        "the ONE interprocedural hop is what lets the real teardown-"
        "helper idiom (engine._dispatch_packed, a shutdown(res) module "
        "function) lint clean; with helper releases not credited every "
        "correctly-paired helper call flags and the clean-tree gate goes "
        "red — killed by test_release_inside_helper_counts (and "
        "test_full_tree_lints_clean_with_concurrency_rules_active)",
    ),
    (
        "fixpoint-stops-at-one-hop",
        "arena/analysis/effects.py",
        "    while changed:  # to fixpoint: one call-graph hop per pass",
        "    if changed:  # one propagation pass only (the v3/v4 shape)",
        "the effect-summary engine must propagate to FIXPOINT over call "
        "edges; stopped after one hop, a 2-hop chain (contract fn -> "
        "helper -> clock) reads clean and the `# deterministic` contract "
        "silently stops meaning transitive — killed by "
        "test_nondeterminism_propagates_over_two_call_hops (the corpus "
        "file IS the 2-hop chain)",
    ),
    (
        "check-then-act-ignores-reacquire",
        "arena/analysis/effects.py",
        "            if rebound:\n"
        "                # Rebinding is the re-check credit: a fresh read under\n"
        "                # a re-acquired lock replaces the stale fact entirely.\n"
        "                facts = {f for f in facts if f[0] not in rebound}",
        "            if rebound:\n"
        "                pass  # re-check credit deliberately dropped",
        "the stale-fact KILL on rebind is what makes the SANCTIONED fix "
        "(re-read the guarded field under the re-acquired lock, act on "
        "the fresh copy) lint clean; without it the double-checked idiom "
        "flags forever and the rule can only be silenced, not satisfied "
        "— killed by test_recheck_under_reacquired_lock_lints_clean",
    ),
    (
        "pure-render-param-reads-flagged-as-hidden",
        "arena/analysis/effects.py",
        '            if root == view or (root != "self" and root in params):\n'
        "                # Reads through the named view or any other parameter\n"
        "                # ARE the contract's declared inputs — never hidden.\n"
        "                continue\n"
        '            if root == "self" and node.attr not in methods:',
        "            if node.attr not in methods:",
        "`# pure-render(view)` means 'renders FROM its inputs': reads "
        "through the named view (and any other parameter) are the "
        "declared data flow; dropping the exemption AND the self-only "
        "gate flags them as hidden state, forcing suppressions onto "
        "every correct render — the real ArenaServer._player_row "
        "would go red and the clean-tree gate with it — killed by "
        "test_pure_render_reading_only_its_view_lints_clean (and "
        "test_full_tree_lints_clean_with_concurrency_rules_active)",
    ),
    (
        "cache-not-invalidated-on-watermark-advance",
        "arena/net/fastpath.py",
        "            entry = self._entries.get(key)\n"
        "            if entry is not None and entry[0] == view_seq:",
        "            entry = self._entries.get(key)\n"
        "            if entry is not None:",
        "the wire byte cache's whole correctness story is the generation "
        "check: a `get` that ignores the view seq serves bytes rendered "
        "from a DEAD view after the watermark advances — stale "
        "leaderboards wearing a fresh-looking envelope — killed by "
        "test_cache_invalidates_when_watermark_advances (a /player read "
        "after an ingest advance must carry the new watermark)",
    ),
    (
        "batch-endpoint-splits-views-across-one-request",
        "arena/serving.py",
        "            view, stale = self._serve_view()\n"
        "            staleness = view.matches_ingested - view.watermark\n"
        "            results = []\n"
        "            for spec in specs:\n"
        "                results.append(self._query_parts(",
        "            results = []\n"
        "            for spec in specs:\n"
        "                view, stale = self._serve_view()\n"
        "                staleness = view.matches_ingested - view.watermark\n"
        "                results.append(self._query_parts(",
        "the batch endpoint sells ONE view across every lookup in the "
        "request (mutually consistent results); choosing a view per spec "
        "lets concurrent ingest split one response across several views "
        "— killed by test_batch_query_answers_every_part_from_one_view "
        "(ingest advances after every refresh, so a per-spec choice "
        "yields differing view_seqs)",
    ),
    (
        "event-loop-read-falls-back-to-blocking-silently",
        "arena/net/server.py",
        "        if fastpath_reads:\n"
        "            self._loop = fastpath.EventLoopFrontEnd(",
        "        if fastpath_reads and False:  # quiet threaded fallback\n"
        "            self._loop = fastpath.EventLoopFrontEnd(",
        "the event loop is the perf tentpole's read front end; a silent "
        "fallback to thread-per-connection passes every functional test "
        "while quietly reverting the 10x — killed by "
        "test_default_front_end_is_the_event_loop (/healthz must report "
        "front_end == eventloop and the loop's named thread must be "
        "live)",
    ),
    (
        "schema-facts-extractor-returns-empty",
        "arena/analysis/schema.py",
        "    return _Facts(frozenset(produced), frozenset(consumed), "
        "arrays, dtypes)",
        "    return _Facts(frozenset(), frozenset(), (), {})",
        "the fact extractor is the front end of all three shape rules; "
        "returning empty facts makes every schema contract vacuously "
        "clean (no produced keys, no consumed keys, no order) while the "
        "rules still 'run' — killed by "
        "test_extract_facts_collects_produced_consumed_arrays_dtypes "
        "(and the bad_schema_drift/bad_undeclared_field corpus "
        "contracts, which stop firing)",
    ),
    (
        "version-bump-check-inverted",
        "arena/analysis/schema.py",
        "            return found > recorded  # a bump is "
        "strictly-greater, never equal",
        "            return found >= recorded  # >= : the recorded "
        "version counts as bumped",
        "a bump means the module constant moved PAST the recorded "
        "version; under >= the unchanged constant (v1 == v1) reads as "
        "already-bumped and every silent drift on a versioned format is "
        "waved through — killed by "
        "test_seeded_manifest_field_add_without_bump_is_flagged (the "
        "seeded manifest field must flag while SNAPSHOT_VERSION sits at "
        "the recorded version)",
    ),
    (
        "replication-boundary-uses-one-hop-not-fixpoint",
        "arena/analysis/schema.py",
        "        while frontier:  # transitive apply closure, to "
        "fixpoint over call edges",
        "        if frontier:  # one hop only: direct callees of the "
        "apply roots",
        "the apply closure must be transitive: a helper two calls below "
        "the `# deterministic` root still replays; a one-hop closure "
        "flags it as outside the boundary, forcing exemptions onto "
        "correct code — killed by "
        "test_two_hop_closure_is_inside_the_boundary (apply -> _stage "
        "-> _commit must lint clean)",
    ),
    (
        "replica-applies-arrival-order-not-sequence-order",
        "arena/net/replica.py",
        "            if self._anchored and seq != self._applied_seq + 1:",
        "            if False:  # trust arrival order",
        "strict sequence order is the whole bit-exactness argument: a "
        "replica that applies whatever order segments arrive in forks "
        "silently from the writer under any reordering — killed by "
        "test_replica_refuses_out_of_sequence_and_diverged_records (a "
        "gapped seq must raise ReplicaError before touching the "
        "engine)",
    ),
    (
        "incremental-manifest-skips-base-chain-validation",
        "arena/serving.py",
        '    if base_manifest.get("checksum_sha256") != '
        'child.get("base_checksum_sha256"):',
        '    if False:  # any base with matching counts will do',
        "an increment must resolve against EXACTLY the base it was cut "
        "from (content identity, not counts); skipping the checksum "
        "link lets a self-consistent impostor base assemble a silently "
        "forked state — killed by "
        "test_restore_rejects_swapped_or_tampered_base_chain (a "
        "same-count different-stream base must be a named reject)",
    ),
    (
        "staleness-slo-never-evaluated",
        "arena/net/replica.py",
        "        self._obs.slo.evaluate()",
        "        pass  # objective registered; burn-rate pull skipped",
        "a registered-but-never-evaluated objective is a dead dashboard "
        "row: the replica reports healthy staleness forever because "
        "nobody pulls the burn rate — killed by "
        "test_replica_staleness_slo_and_profiler_roles (the engine's "
        "evaluations counter must advance while the reader tails)",
    ),
    (
        "tenant-key-dropped-from-segment-sort",
        "arena/tenancy.py",
        "    return ids + np.int32(tenant * players_per_tenant)",
        "    return ids + np.int32(0 * players_per_tenant)",
        "the composite id IS the tenant key: drop the tenant offset and "
        "every tenant's matches collapse into tenant 0's segment range, "
        "so one shared kernel silently cross-pollinates leaderboards — "
        "killed by test_store_groups_tenant_major (stored composite ids "
        "must land in each submitting tenant's id range and idle "
        "tenants' rating rows must stay untouched)",
    ),
    (
        "tenant-bucket-never-padded",
        "arena/tenancy.py",
        "    return max(min_bucket, _pow2_ceil(max(int(num_tenants), 1)))",
        "    return max(int(num_tenants), 1)",
        "the pow2 tenant bucket is the zero-recompile contract: size "
        "state to the exact tenant count and every onboarded tenant "
        "changes the jitted ratings shape, retracing the kernel — "
        "killed by test_tenant_growth_within_bucket_zero_recompiles "
        "(growing 5 -> 8 tenants must keep the bucket and add zero "
        "compiles)",
    ),
    (
        "wire-tenant-validation-skipped",
        "arena/engine.py",
        "    if not 0 <= t < num_tenants:",
        "    if False:",
        "_validate_tenant is the wire sanitizer for the tenant key: "
        "skip the range check and a submit to an out-of-range tenant "
        "composites into some other tenant's (or nobody's) id space "
        "instead of 400ing at the door — killed by "
        "test_wire_unknown_tenant_rejected (tenant 5 and 99 on a "
        "3-tenant arena must 400 on every endpoint and apply nothing)",
    ),
    (
        "proposal-ignores-CI-width",
        "arena/match/matchmaker.py",
        "    eff = widths + scale / (1.0 + counts)",
        "    eff = scale / (1.0 + counts)",
        "the effective uncertainty must blend the live bootstrap widths "
        "with the count-decaying prior: drop the widths and the active "
        "policy ranks by match count alone, so a settled-but-wide "
        "interval never attracts the match that would shrink it — "
        "killed by test_pair_components_matches_numpy_oracle (the "
        "combined-width and overlap surfaces must equal the numpy "
        "oracle that includes the widths term)",
    ),
    (
        "closed-loop-gate-skipped",
        "arena/bench_arena.py",
        "    if advantage < min_advantage:",
        "    if False:",
        "the matchloop's convergence verdict is the PR's acceptance "
        "criterion: skip the advantage comparison and an active policy "
        "that converges SLOWER than random pairing still exits 0 with "
        "a green arena_matchloop line — killed by "
        "test_matchloop_convergence_gate_is_hard (an impossible "
        "MIN_ADVANTAGE must produce rc 2 and the "
        "arena_bench_matchloop_gate_failure line, never a result line)",
    ),
    (
        "match-envelope-omits-watermark",
        "arena/match/matchmaker.py",
        '        "watermark": view.watermark,',
        '        "view_watermark": view.watermark,',
        "the /match payload's watermark is what make_response promotes "
        "into the envelope: rename it and the envelope silently falls "
        "back to the LIVE matches_applied counter, stamping proposals "
        "with freshness the proposing view does not have — killed by "
        "test_match_envelope_watermark_is_the_views (under a staleness "
        "allowance the envelope watermark must equal the view's, not "
        "the live counter's)",
    ),
)


def make_copy(dest: pathlib.Path) -> None:
    for name in COPIED:
        src = REPO / name
        if src.is_dir():
            shutil.copytree(
                src, dest / name, ignore=shutil.ignore_patterns("__pycache__")
            )
        else:
            shutil.copy2(src, dest / name)


def run_suite(copy: pathlib.Path) -> subprocess.CompletedProcess:
    return subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "tests/",
            "-x",
            "-q",
            "--no-header",
            # The audit measures the tier-1 surface; the slow-marked
            # full-size benchmark would add minutes per mutant while
            # enforcing no honesty property.
            "-m",
            "not slow",
            "-p",
            "no:cacheprovider",
            # See module docstring: the pattern-consistency test fails
            # under ANY mutation and must not count as a kill.
            "--ignore",
            str(copy / "tests" / "test_mutation_audit.py"),
        ],
        capture_output=True,
        text=True,
        cwd=copy,
        timeout=600,
    )


def main() -> int:
    try:
        return _run_audit()
    except Exception as exc:  # noqa: BLE001 — rc must stay a verdict
        # Without this, a hung suite subprocess (TimeoutExpired) or a
        # copy failure would exit with Python's default rc 1 — reading
        # to an rc-only consumer as "a mutant survived" when nothing
        # was measured. Same collision class the gate's rc 4 exists
        # to prevent.
        print(
            json.dumps(
                {
                    "check": "mutation_audit",
                    "error": "audit_crashed",
                    "detail": bench.exc_detail(exc),
                }
            )
        )
        return 3


def _run_audit() -> int:
    survived = []
    root = pathlib.Path(tempfile.mkdtemp(prefix="graft-mutation-audit-"))
    copy = root / "repo"
    copy.mkdir()
    try:
        make_copy(copy)
        # Sanity: the unmutated copy must be green, or every verdict
        # below is noise.
        clean = run_suite(copy)
        if clean.returncode != 0:
            print(
                json.dumps(
                    {
                        "check": "mutation_audit",
                        "error": "clean_copy_suite_red",
                        "detail": clean.stdout.strip().splitlines()[-1:],
                    }
                )
            )
            return 2
        for name, relpath, old, new, property_broken in MUTATIONS:
            target = copy / relpath
            pristine = target.read_text()
            if old not in pristine:
                # test_mutation_audit.py should have caught this first.
                survived.append(
                    {
                        "name": name,
                        "reason": "pattern_missing",
                        "property": property_broken,
                    }
                )
                print(f"STALE    {name}: pattern missing", file=sys.stderr)
                continue
            target.write_text(pristine.replace(old, new, 1))
            try:
                proc = run_suite(copy)
            finally:
                target.write_text(pristine)
            if proc.returncode == 0:
                survived.append({"name": name, "property": property_broken})
                print(f"SURVIVED {name}", file=sys.stderr)
            else:
                print(f"KILLED   {name}", file=sys.stderr)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print(
        json.dumps(
            {
                "check": "mutation_audit",
                "total": len(MUTATIONS),
                "killed": len(MUTATIONS) - len(survived),
                "survived": survived,
            }
        )
    )
    return 0 if not survived else 1


if __name__ == "__main__":
    sys.exit(main())
