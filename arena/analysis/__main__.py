"""`python -m arena.analysis` — run jaxlint over the given paths."""

import sys

from arena.analysis.jaxlint import main

if __name__ == "__main__":
    sys.exit(main())
