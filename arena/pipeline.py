"""Overlapped ingest: a background packing thread behind a bounded queue.

PR 3 made the host side of ingest incremental (mergeable CSR grouping,
reusable double-buffered staging slots), but fill and dispatch still
run on ONE thread: every `ArenaEngine.ingest()` call pays the NumPy
packing cost (delta sort + slot grouping, the dominant host cost) and
the device dispatch back to back. This module splits them across the
thread boundary the double buffer was built for:

- **`IngestPipeline`** owns a background PACKER thread. `submit()`
  enqueues a validated raw batch on a bounded ingest queue; the packer
  pops batches in FIFO order, merges each into the engine's mergeable
  CSR store and fills the next `StagingBuffers` slot (all host-side
  NumPy), and hands the staged `PackedBatch` to a ready queue. The
  DISPATCH half — the jitted rating update — runs on whichever thread
  calls `submit()`/`flush()`/`close()` (in practice the main thread),
  so the packer fills one slot while the main thread dispatches the
  other. Order is preserved end to end (one packer, FIFO queues, one
  dispatch at a time), so the ratings are BIT-EXACT equal to the
  synchronous `ingest()` path — same staged layout, same jitted
  function, same sequence (pinned by tests and by the bench's hard
  equivalence gate).

- **Backpressure**: the ingest queue is bounded (`capacity`). When it
  is full, the `"block"` policy makes `submit()` dispatch ready work
  and wait for space (lossless — the default), while `"drop-oldest"`
  evicts the oldest still-raw batch and counts it in
  `dropped_batches`/`dropped_matches` (bounded-staleness traffic
  shedding; a dropped batch never touched the match store, so history
  and ratings stay consistent). Batches the packer has already merged
  are ALWAYS dispatched — only raw, un-merged batches can be dropped.

- **Shutdown/drain**: `flush()` blocks until everything submitted has
  been packed and dispatched. `close(drain=True)` (the default)
  flushes, then stops and joins the packer; `close(drain=False)` drops
  the raw queue first (counted), still dispatches everything already
  past the store merge, then joins; `close(spill=True)` extracts the
  raw queue instead of dropping it and returns the batches (validated
  int32 array pairs, FIFO order) so a durable snapshot can persist
  them — the serving layer's restart-mid-stream path. Every blocking
  wait re-checks packer liveness, so a dead or never-started packer
  thread raises `PipelineError` instead of hanging the caller.

- **Observability** (PR 6): the pipeline reads the engine's
  `arena.obs` handle per event — `pipeline.enqueue_wait` /
  `pipeline.pack` / `pipeline.dispatch` spans, an enqueue-wait
  histogram, and policy-labeled dropped/spilled registry counters
  (`arena_pipeline_dropped_batches_total{policy=...}` etc.) that
  `ArenaServer.stats()` reports and that survive pipeline restarts.
  The internal integer counters below remain the source of truth for
  `pending()`; the registry is the reporting schema.

- **Causal tracing** (PR 7): every queue item carries the submitting
  batch's `TraceContext` (captured from the engine's `batch.submit`
  root span), and the packer/dispatcher re-attach it around their
  work — so `pipeline.pack`, the CSR merge/compaction/staging inside
  it, and `pipeline.dispatch` all parent into the SAME trace as the
  submit, across the thread boundary (the Chrome export draws the
  flow arrows). A batch that is shed instead of processed gets an
  explicit terminal `pipeline.dropped` marker span in its trace —
  a dropped request's trace ENDS, it never dangles. Submit-path
  counters and the queue-depth gauge carry a `producer` label
  (default "local"): the multi-producer front door (ROADMAP item 1)
  lands on this schema instead of renaming metrics later. Drops,
  spills, and queue-depth samples also land in the bounded
  `Observability.event` log the flight recorder bundles.

On this image's single host core the two threads share one CPU, so the
overlap cannot beat the synchronous path in wall clock (the bench
reports what it measures, with `host_cores` in the line); the
pipeline's value here is the concurrency-correct shape — bounded queue,
slot lifetime discipline, drain protocol — that a real accelerator
host needs, where device dispatch is idle host time the packer can use.
"""

import threading
import time
from collections import deque

from arena.obs import context as trace_context

POLICY_BLOCK = "block"
POLICY_DROP_OLDEST = "drop-oldest"
POLICIES = (POLICY_BLOCK, POLICY_DROP_OLDEST)

# Raw batches tolerated in the ingest queue before backpressure kicks
# in. Small by design: the queue bounds rating staleness, not memory.
DEFAULT_QUEUE_CAPACITY = 8

# Wait quantum for every blocking loop: each wakeup re-checks packer
# liveness and recorded errors, so no caller can hang on a dead thread.
_WAIT_S = 0.05

# The packer thread's NAME is part of the observability contract: the
# sampling profiler (arena/obs/profile.py) classifies threads into
# roles by these names, so "the packer spends its wall clock in X"
# survives restarts. Rename here and the profiler's role table moves
# in the same commit, or the profile silently degrades to "other".
PACKER_THREAD_NAME = "arena-ingest-packer"


class PipelineError(RuntimeError):
    """The pipeline cannot make progress (packer dead or errored)."""


class IngestPipeline:  # protocol: close
    """Background packing thread + bounded ingest queue for one engine.

    Built lazily by `ArenaEngine.ingest_async()` (or explicitly via
    `ArenaEngine.start_pipeline(capacity=..., policy=...)`). The
    pipeline owns no rating state: it moves batches through the
    engine's own store/staging/update path, which is what makes the
    async ratings bit-exact to the sync ones.
    """

    def __init__(self, engine, capacity=DEFAULT_QUEUE_CAPACITY,
                 policy=POLICY_BLOCK, producer="local"):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        if policy not in POLICIES:
            raise ValueError(f"unknown queue policy {policy!r}; pick one of {POLICIES}")
        if not producer or not isinstance(producer, str):
            raise ValueError(f"producer label must be a non-empty str, got {producer!r}")
        self._eng = engine
        self.capacity = capacity
        self.policy = policy
        # Metric label for the submit path. One in-process producer
        # today; ROADMAP item 1's multi-producer front door keys its
        # per-producer streams by this label instead of renaming the
        # counters/gauges later.
        self.producer = producer
        # One condition guards the queues, lifecycle flags, and the
        # counters below — producer threads, the packer, and whichever
        # thread dispatches all touch them. The `guarded_by`
        # annotations are the jaxlint `unguarded-shared-write`
        # contract: any write outside __init__ must hold `_cv`.
        self._cv = threading.Condition()
        self._raw = deque()  # guarded_by: _cv  ((winners, losers, ctx), not packed)
        self._ready = deque()  # guarded_by: _cv  ((PackedBatch, ctx), not dispatched)
        # Serializes pop-from-ready + apply so concurrent dispatchers
        # (submit draining while flush drains) keep FIFO order.
        self._dispatch_lock = threading.Lock()
        self._closed = False  # guarded_by: _cv
        self._packing = False  # guarded_by: _cv  (packer holds a popped batch)
        self._error = None  # guarded_by: _cv
        self.submitted = 0  # guarded_by: _cv
        self.completed = 0  # guarded_by: _cv
        self.dropped_batches = 0  # guarded_by: _cv
        self.dropped_matches = 0  # guarded_by: _cv
        self.spilled_batches = 0  # guarded_by: _cv
        self.spilled_matches = 0  # guarded_by: _cv
        # Host-pack vs device-dispatch breakdown (the bench reports it).
        # host_pack_s is packer-thread-private; dispatch_s is serialized
        # by the dispatch lock, not the condition.
        self.host_pack_s = 0.0
        self.dispatch_s = 0.0  # guarded_by: _dispatch_lock
        self._thread = threading.Thread(
            target=self._pack_loop, name=PACKER_THREAD_NAME, daemon=True
        )
        self._thread.start()

    # --- accounting --------------------------------------------------

    def _obs(self):
        """The engine's observability handle, read PER EVENT so a
        serving layer upgrading the engine's obs mid-life (set_obs)
        is picked up without rewiring the pipeline."""
        return self._eng.obs

    def _count_dropped(self, batches, matches):
        """Registry half of drop accounting: the internal ints above
        stay the source of truth for pending() (they are read under
        _cv as one consistent set), and every drop ALSO lands in the
        registry as policy+producer-labeled counters — the one schema
        `ArenaServer.stats()` and the soak bench report from. Counts
        survive pipeline restarts there, unlike these attributes."""
        obs = self._obs()
        obs.counter(
            "arena_pipeline_dropped_batches_total", policy=self.policy,
            producer=self.producer,
        ).inc(batches)
        obs.counter(
            "arena_pipeline_dropped_matches_total", policy=self.policy,
            producer=self.producer,
        ).inc(matches)
        obs.event("drop", policy=self.policy, producer=self.producer,
                  batches=batches, matches=matches)

    def _end_dropped_trace(self, ctx):
        """Terminal marker for a shed batch's trace: a zero-duration
        `pipeline.dropped` span parented into the batch's own context,
        so the trace ENDS with an explicit verdict instead of dangling
        (tier-1 pins it under both backpressure policies)."""
        self._obs().tracer.record_span(
            "pipeline.dropped", time.perf_counter(), 0.0, context=ctx
        )

    def pending(self):
        """Batches submitted but not yet dispatched (or dropped)."""
        with self._cv:
            return self._pending_locked()

    def _pending_locked(self):
        return (
            self.submitted
            - self.completed
            - self.dropped_batches
            - self.spilled_batches
        )

    def _raise_if_failed_locked(self):
        if self._error is not None:
            raise PipelineError(
                f"ingest pipeline failed in the packer thread: {self._error!r}"
            ) from self._error

    def _check_packer_locked(self):
        """Raise if pending work needs a packer that is not running."""
        self._raise_if_failed_locked()
        if (self._raw or self._packing) and not self._thread.is_alive():
            raise PipelineError(
                "packer thread is not running but batches are queued; "
                "the pipeline cannot drain"
            )

    # --- producer side ----------------------------------------------

    def submit(self, winners, losers, producer=None):
        """Enqueue one VALIDATED batch (int32 arrays, ids in range).

        Validation happens in `ArenaEngine.ingest_async` on the calling
        thread so a malformed batch raises at the call site with no
        state change. While waiting on a full queue (block policy) the
        caller dispatches ready work — backpressure can never deadlock
        against a packer waiting for a staging slot.

        `producer` overrides the pipeline's own label for THIS batch's
        submit-path counters — the multi-producer front door
        (`arena/net/frontdoor.py`) feeds one pipeline but counts each
        batch under its original producer, so the per-producer streams
        stay visible in the one metric schema.
        """
        label = producer if producer is not None else self.producer
        ctx = trace_context.current()  # the batch.submit root (or None)
        wait_t0 = None
        while True:
            with self._cv:
                if self._closed:
                    raise PipelineError("pipeline is closed; start a new one")
                self._raise_if_failed_locked()
                if len(self._raw) < self.capacity:
                    self._raw.append((winners, losers, ctx))
                    self.submitted += 1
                    depth = len(self._raw)
                    self._cv.notify_all()
                    break
                if self.policy == POLICY_DROP_OLDEST:
                    dw, _dl, dctx = self._raw.popleft()
                    self.dropped_batches += 1
                    self.dropped_matches += int(dw.shape[0])
                    self._count_dropped(1, int(dw.shape[0]))
                    self._end_dropped_trace(dctx)
                    continue
                self._check_packer_locked()
            if wait_t0 is None:
                wait_t0 = time.perf_counter()
            # Block policy, queue full: make progress instead of
            # spinning — dispatch one ready batch if there is one
            # (frees a staging slot, letting the packer advance).
            if not self._dispatch_one():
                with self._cv:
                    self._cv.wait(_WAIT_S)
        obs = self._obs()
        obs.counter(
            "arena_pipeline_submitted_batches_total", producer=label
        ).inc()
        obs.gauge(
            "arena_pipeline_queue_depth", producer=self.producer
        ).set(float(depth))
        obs.event("queue_depth", depth=depth, producer=self.producer)
        if wait_t0 is not None:
            # Backpressure made this submit wait (dispatching ready
            # work counts as waiting: the caller could not enqueue).
            waited = time.perf_counter() - wait_t0
            obs.histogram(
                "arena_pipeline_enqueue_wait_seconds", producer=label
            ).record(waited)
            obs.tracer.record_span("pipeline.enqueue_wait", wait_t0, waited)
        # Overlap: opportunistically dispatch whatever the packer has
        # already staged while the caller is here anyway.
        while self._dispatch_one():
            pass

    # --- dispatch side (runs on the submitting/flushing thread) ------

    def _dispatch_one(self):
        """Dispatch the oldest ready batch. Returns True if one ran."""
        with self._dispatch_lock:
            with self._cv:
                if not self._ready:
                    return False
                packed, ctx = self._ready.popleft()
            t0 = time.perf_counter()
            try:
                # Re-attach the batch's own context: whichever thread
                # happens to dispatch, the span parents into the
                # SUBMITTING batch's trace, not the current caller's.
                with trace_context.attach(ctx), \
                        self._obs().span("pipeline.dispatch"):
                    self._eng._dispatch_packed(packed)
            finally:
                self.dispatch_s += time.perf_counter() - t0
                with self._cv:
                    self.completed += 1
                    self._cv.notify_all()
        return True

    def flush(self):
        """Block until every submitted batch is packed AND dispatched."""
        while True:
            if self._dispatch_one():
                continue
            with self._cv:
                self._raise_if_failed_locked()
                if self._pending_locked() == 0:
                    return
                self._check_packer_locked()
                self._cv.wait(_WAIT_S)

    def close(self, drain=True, spill=False):  # schema: pipeline-spill@v1
        """Stop the pipeline and join the packer thread.

        drain=True processes everything still queued (lossless
        shutdown). drain=False drops batches still in the RAW queue
        (counted in dropped_batches) — but batches the packer already
        merged into the match store are always dispatched, so the
        store and the ratings can never disagree about which matches
        happened.

        spill=True (implies drain=False for the raw queue) EXTRACTS
        the still-raw batches instead of dropping them and returns
        them, FIFO order preserved, as a list of validated
        `(winners, losers)` int32 array pairs — exactly what a durable
        snapshot needs to persist so a restarted server can resubmit
        them and resume mid-stream (see `arena/serving.py`). Spilled
        batches are NOT counted as dropped: they left this process's
        queue but not the logical stream. Returns [] when not
        spilling.
        """
        spilled = []
        with self._cv:
            self._closed = True
            if spill:
                while self._raw:
                    sw, sl, _sctx = self._raw.popleft()
                    self.spilled_batches += 1
                    self.spilled_matches += int(sw.shape[0])
                    spilled.append((sw, sl))
                if spilled:
                    obs = self._obs()
                    obs.counter(
                        "arena_pipeline_spilled_batches_total",
                        producer=self.producer,
                    ).inc(len(spilled))
                    obs.counter(
                        "arena_pipeline_spilled_matches_total",
                        producer=self.producer,
                    ).inc(self.spilled_matches)
                    obs.event("spill", producer=self.producer,
                              batches=len(spilled),
                              matches=self.spilled_matches)
            elif not drain:
                dropped_b = dropped_m = 0
                while self._raw:
                    dw, _dl, dctx = self._raw.popleft()
                    self.dropped_batches += 1
                    self.dropped_matches += int(dw.shape[0])
                    dropped_b += 1
                    dropped_m += int(dw.shape[0])
                    self._end_dropped_trace(dctx)
                if dropped_b:
                    self._count_dropped(dropped_b, dropped_m)
            self._cv.notify_all()
        try:
            self.flush()
        finally:
            with self._cv:
                self._cv.notify_all()
            self._thread.join(timeout=10.0)
        return spilled

    # --- the packer thread -------------------------------------------

    def _pack_loop(self):
        while True:
            with self._cv:
                while not self._raw and not self._closed:
                    self._cv.wait()
                if not self._raw:
                    return  # closed and fully drained
                w, l, ctx = self._raw.popleft()
                self._packing = True
                self._cv.notify_all()  # queue space for blocked submits
            try:
                t0 = time.perf_counter()
                # Adopt the submitting batch's trace on THIS thread:
                # the pack span (and the CSR merge/compaction/staging
                # spans inside it) parent into the producer's
                # batch.submit root across the thread boundary.
                with trace_context.attach(ctx), \
                        self._obs().span("pipeline.pack"):
                    packed = self._eng._pack_for_pipeline(w, l)
                self.host_pack_s += time.perf_counter() - t0
            except BaseException as exc:  # noqa: BLE001 — must surface on the caller
                with self._cv:
                    self._error = exc
                    self._packing = False
                    # The failed batch and everything behind it is
                    # dropped; flush()/submit() re-raise on next call.
                    dropped_b = 1 + len(self._raw)
                    dropped_m = int(w.shape[0]) + sum(
                        int(rw.shape[0]) for rw, _rl, _rc in self._raw
                    )
                    self.dropped_batches += dropped_b
                    self.dropped_matches += dropped_m
                    self._count_dropped(dropped_b, dropped_m)
                    self._end_dropped_trace(ctx)
                    for _rw, _rl, rctx in self._raw:
                        self._end_dropped_trace(rctx)
                    self._raw.clear()
                    self._cv.notify_all()
                return
            with self._cv:
                if packed is not None:
                    self._ready.append((packed, ctx))
                else:
                    self.completed += 1  # empty batch: nothing to dispatch
                self._packing = False
                self._cv.notify_all()
