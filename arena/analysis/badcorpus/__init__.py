"""Bad-example corpus for jaxlint: one file per rule, each written to
trip exactly its own rule. NEVER imported at runtime — these modules
exist to be parsed by the linter (the tier-1 test asserts every shipped
rule fires at least once here, and `python -m arena.analysis` exits
non-zero over this directory). Default directory walks skip it, so the
clean-tree lint stays clean."""
