"""SLO burn-rate engine contracts (arena/obs/slo.py).

The load-bearing properties:

- the burn-rate math: burn = error_fraction / (1 - target), and an
  alert FIRES only when the fast AND slow windows both exceed the
  threshold — the mutation audit carries a
  burn-rate-alert-threshold-inverted mutant (both comparisons flipped
  to <=, i.e. an engine that pages on health and sleeps through an
  incident); test_burn_rate_alert_fires_only_above_threshold is its
  named kill (it pins BOTH directions: silent at zero burn, firing
  above threshold);
- latency SLOs: the error fraction is the windowed share of
  observations over the threshold's log2 bucket bound;
- transitions are edge-triggered `slo_alert` events in the bounded
  event log, carrying the trace-id exemplar of the offending bucket
  (resolvable via `Tracer.trace`), and recovery transitions back to ok
  while `alerts_fired` stays sticky;
- `ArenaServer.stats()` embeds the evaluation as its `slo` block with
  ops-thread health folded in.

Fake-clock windows throughout: no sleeps, no alerting thread (the
engine is pull-based by design).
"""

import pytest

from arena import obs as obs_pkg
from arena.obs.metrics import Registry
from arena.obs.slo import (
    DEFAULT_BURN_THRESHOLD,
    NullSLOEngine,
    SLO,
    SLOEngine,
    SLOError,
    Selector,
    default_slos,
)
from arena.obs.windows import SlidingWindow
from arena.serving import ArenaServer


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def make_engine(slos, intervals=12, interval_s=5.0, obs=None):
    reg = obs.registry if obs is not None else Registry()
    clock = FakeClock()
    win = SlidingWindow(
        reg, intervals=intervals, interval_s=interval_s, clock=clock
    )
    return reg, clock, win, SLOEngine(win, slos=slos, obs=obs)


AVAIL = lambda **kw: SLO(  # noqa: E731 — tiny test factory
    "deliver",
    target=0.999,
    good=Selector("arena_test_good_total"),
    bad=Selector("arena_test_bad_total"),
    **kw,
)


# --- the burn-rate math (the mutation-audit kill) --------------------------


def test_burn_rate_alert_fires_only_above_threshold():
    """Named kill for the audit's burn-rate-alert-threshold-inverted
    mutant (>= flipped to <=): the alert must stay SILENT while the
    burn is under the threshold and must FIRE once both windows exceed
    it — an inverted engine fails both halves at once."""
    reg, clock, win, eng = make_engine([AVAIL()])
    good = reg.counter("arena_test_good_total")
    bad = reg.counter("arena_test_bad_total")

    # Healthy traffic: tiny burn (1 bad / 10000 => frac 1e-4, burn 0.1).
    good.inc(9999)
    bad.inc(1)
    out = eng.evaluate()
    obj = out["objectives"]["deliver"]
    assert obj["burn_slow"] < DEFAULT_BURN_THRESHOLD
    assert obj["state"] == "ok"
    assert out["alerts_active"] == 0
    assert eng.alerts_fired() == 0

    # Incident: half the matches drop => frac ~0.5, burn ~500 >> 14.4.
    bad.inc(10000)
    out = eng.evaluate()
    obj = out["objectives"]["deliver"]
    assert obj["burn_fast"] > DEFAULT_BURN_THRESHOLD
    assert obj["burn_slow"] > DEFAULT_BURN_THRESHOLD
    assert obj["state"] == "firing"
    assert out["alerts_active"] == 1
    assert eng.alerts_fired() == 1


def test_alert_requires_fast_and_slow_agreement():
    """Multi-window: a burst that has already LEFT the fast window
    cannot page, however much slow-window budget it burned — the
    incident must be happening *now*."""
    reg, clock, win, eng = make_engine(
        [AVAIL()], intervals=12, interval_s=5.0
    )
    reg.counter("arena_test_good_total").inc(100)
    reg.counter("arena_test_bad_total").inc(900)
    # Rotate the burst out of the 1-interval fast window (but keep it
    # well inside the slow one).
    clock.tick(5.0)
    win.advance()
    clock.tick(5.0)
    win.advance()
    out = eng.evaluate()
    obj = out["objectives"]["deliver"]
    assert obj["burn_slow"] > DEFAULT_BURN_THRESHOLD
    assert obj["burn_fast"] == 0.0
    assert obj["state"] == "ok"
    assert eng.alerts_fired() == 0


def test_empty_window_burns_no_budget():
    """No traffic is 0.0 error fraction, not 0/0: a freshly started
    (or idle) service must not page on silence."""
    _reg, _clock, _win, eng = make_engine([AVAIL()])
    out = eng.evaluate()
    obj = out["objectives"]["deliver"]
    assert obj["burn_fast"] == 0.0
    assert obj["burn_slow"] == 0.0
    assert obj["state"] == "ok"


def test_latency_slo_error_fraction_over_threshold_bucket():
    slo = SLO(
        "read-latency",
        target=0.9,
        latency=Selector("arena_test_seconds"),
        threshold_s=0.25,
    )
    reg, clock, win, eng = make_engine([slo])
    hist = reg.histogram("arena_test_seconds")
    for _ in range(80):
        hist.record(0.01)
    for _ in range(20):
        hist.record(5.0)
    out = eng.evaluate()
    obj = out["objectives"]["read-latency"]
    # 20% of requests blew the threshold against a 10% budget: burn 2.
    assert obj["error_frac_fast"] == pytest.approx(0.2)
    assert obj["burn_fast"] == pytest.approx(2.0)
    assert obj["state"] == "ok"  # 2.0 < 14.4: slow, not page-worthy


# --- transitions, events, exemplars ----------------------------------------


def test_transitions_post_events_with_resolvable_exemplar():
    """ok->firing and firing->ok are edge-triggered `slo_alert` events
    (exactly one each, not one per evaluate), the firing record carries
    the exemplar trace id of the offending histogram bucket, and
    `alerts_fired` stays sticky after recovery."""
    obs = obs_pkg.Observability()
    slo = AVAIL(exemplar=Selector("arena_test_magnitude"))
    reg, clock, win, eng = make_engine([slo], obs=obs)
    good = obs.counter("arena_test_good_total")
    bad = obs.counter("arena_test_bad_total")
    mag = obs.histogram("arena_test_magnitude", base=1.0)

    good.inc(1000)
    eng.evaluate()
    # The incident, with the exemplar recorded the way the front door
    # records shed magnitudes: the offending batch's own trace id.
    mag.record(4096.0, trace_id=77)
    bad.inc(5000)
    eng.evaluate()
    eng.evaluate()  # still firing: NO second event (edge, not level)

    alerts = [e for e in obs.events if e["kind"] == "slo_alert"]
    assert len(alerts) == 1
    assert alerts[0]["slo"] == "deliver"
    assert alerts[0]["state"] == "firing"
    assert alerts[0]["trace_id"] == 77
    assert eng.firings("deliver")[-1]["trace_id"] == 77

    # Recovery: rotate the incident out of both windows entirely.
    for _ in range(13):
        clock.tick(5.0)
        win.advance()
    out = eng.evaluate()
    assert out["objectives"]["deliver"]["state"] == "ok"
    alerts = [e for e in obs.events if e["kind"] == "slo_alert"]
    assert len(alerts) == 2
    assert alerts[1]["state"] == "ok"
    assert eng.alerts_fired() == 1  # sticky: the page happened
    assert out["alerts_active"] == 0


def test_default_slos_cover_the_serving_tier():
    names = {s.name for s in default_slos()}
    assert names == {
        "wire-availability", "wire-read-latency", "submit-delivery"
    }
    for s in default_slos():
        assert s.burn_threshold == DEFAULT_BURN_THRESHOLD
        payload = s.to_payload()
        assert payload["name"] == s.name
        assert payload["kind"] in ("availability", "latency")


def test_malformed_slos_are_rejected():
    with pytest.raises(SLOError):
        SLO("x", target=1.5, good=Selector("g"), bad=Selector("b"))
    with pytest.raises(SLOError):
        SLO("x", target=0.9)  # neither kind declared
    with pytest.raises(SLOError):
        SLO("x", target=0.9, latency=Selector("l"))  # no threshold_s
    with pytest.raises(SLOError):
        SLOEngine(object(), slos=[AVAIL(), AVAIL()])  # duplicate names


def test_null_engine_is_a_true_noop_twin():
    null = NullSLOEngine()
    out = null.evaluate()
    assert out["objectives"] == {}
    assert out["alerts_active"] == 0
    assert null.alerts_fired() == 0
    assert null.firings() == []


# --- the stats() wiring ----------------------------------------------------


def test_server_stats_embeds_the_slo_block():
    """`ArenaServer.stats()` carries one live SLO evaluation with
    window/profiler health folded in — the operator's one-stop
    am-I-okay read (and the /debug/slo payload's source of truth)."""
    obs = obs_pkg.Observability()
    srv = ArenaServer(num_players=8, obs=obs)
    try:
        block = srv.stats()["slo"]
        assert set(block["objectives"]) == {
            "wire-availability", "wire-read-latency", "submit-delivery"
        }
        assert block["alerts_active"] == 0
        assert block["errors"] == []
        assert block["healthy"] is True
        assert block["window_health"]["error"] is None
        assert block["profiler_health"]["error"] is None
    finally:
        srv.close()
