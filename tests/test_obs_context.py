"""Causal tracing contracts: trace-context propagation, exemplars,
and the flight recorder (arena/obs/context.py, tracing ids,
metrics exemplars, arena/obs/debug.py).

The load-bearing properties:

- spans form TREES: nesting on one thread links parent→child; a
  context shipped across the pipeline queue links the packer/dispatch
  spans back to the producer's `batch.submit` root (block AND
  drop-oldest policies);
- a dropped batch's trace ENDS with an explicit `pipeline.dropped`
  marker — never a dangling chain;
- span ids are monotonic and survive ring wraparound; a kept child
  whose parent row was evicted classifies as `evicted-parent` (a
  documented information loss), never as `dangling` (a bug) — and the
  Chrome export re-roots it under a synthetic `evicted-parent` event;
- histogram exemplars land in the recorded value's OWN bucket (the
  mutation audit carries a wrong-bucket mutant;
  test_exemplar_lands_in_recorded_values_bucket is its named kill) and
  stay bucket-consistent under N concurrent recording threads;
- in a mini soak (async ingest + queries + snapshot) every recorded
  span is reachable from a root, zero dangling orphans, and the p99
  query-latency exemplar resolves to a real recorded trace — the
  ISSUE 8 acceptance criterion, tier-1-sized;
- `dump_debug_bundle` writes one complete, atomic postmortem directory
  (the audit carries an omits-registry-dump mutant;
  test_debug_bundle_contains_registry_dump is its named kill).
"""

import json
import threading

import numpy as np

from arena import obs as obs_pkg
from arena.engine import ArenaEngine
from arena.obs import TraceContext
from arena.obs.debug import dump_debug_bundle
from arena.obs.metrics import Histogram, Registry
from arena.obs.tracing import Tracer
from arena.serving import ArenaServer

P = 40


def make_matches(n, num_players=P, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.integers(0, num_players, n).astype(np.int32)
    b = ((a + 1 + rng.integers(0, num_players - 1, n)) % num_players).astype(
        np.int32
    )
    return a, b


def _by_name(recs, name):
    return [r for r in recs if r.name == name]


# --- in-thread span trees ---------------------------------------------------


def test_nested_spans_link_parent_child_in_one_trace():
    tr = Tracer(capacity=32)
    with tr.span("root") as root:
        with tr.span("mid") as mid:
            with tr.span("leaf"):
                pass
    recs = {r.name: r for r in tr.spans()}
    assert recs["root"].parent_id == 0
    assert recs["mid"].parent_id == recs["root"].span_id
    assert recs["leaf"].parent_id == recs["mid"].span_id
    assert (
        recs["root"].trace_id
        == recs["mid"].trace_id
        == recs["leaf"].trace_id
        == root.trace_id
        == mid.trace_id
    )
    # Two sibling roots get DISTINCT traces.
    with tr.span("other"):
        pass
    other = _by_name(tr.spans(), "other")[0]
    assert other.trace_id != root.trace_id and other.parent_id == 0


def test_attach_adopts_a_foreign_context_and_none_is_noop():
    tr = Tracer(capacity=32)
    with tr.span("producer") as prod:
        ctx = obs_pkg.current_context()
        assert ctx == TraceContext(prod.trace_id, prod.span_id)
    # Another "thread" (same thread, empty stack) attaches the context.
    assert obs_pkg.current_context() is None
    with obs_pkg.attach(ctx):
        with tr.span("consumer"):
            pass
    with obs_pkg.attach(None):  # the null path: explicit no-op
        assert obs_pkg.current_context() is None
    consumer = _by_name(tr.spans(), "consumer")[0]
    assert consumer.trace_id == prod.trace_id
    assert consumer.parent_id == prod.span_id


def test_trace_returns_exactly_one_requests_spans():
    tr = Tracer(capacity=32)
    with tr.span("a"):
        with tr.span("a.child"):
            pass
    with tr.span("b"):
        pass
    a_root = _by_name(tr.spans(), "a")[0]
    names = {r.name for r in tr.trace(a_root.trace_id)}
    assert names == {"a", "a.child"}


# --- wraparound, monotonic ids, orphan classification -----------------------


def test_evicted_parent_is_classified_not_dangling():
    """Children recorded AFTER their root (the pipeline's dispatch
    shape) survive the root's eviction: monotonic ids classify the
    missing parent as `evicted-parent`, and the Chrome export re-roots
    them under a synthetic event instead of leaving dangling ids."""
    tr = Tracer(capacity=4)
    with tr.span("root") as root:
        pass
    ctx = TraceContext(root.trace_id, root.span_id)
    for i in range(6):  # evicts the root's row; ids keep growing
        tr.record_span(f"late{i}", float(i), 0.1, context=ctx)
    kept = {r.span_id for r in tr.spans()}
    assert root.span_id not in kept  # the root really was evicted
    orphaned = tr.orphans()
    assert orphaned, "evicted root must orphan its late children"
    assert all(reason == "evicted-parent" for _r, reason in orphaned)
    events = tr.export_chrome_trace()
    synthetic = [e for e in events if e["name"] == "evicted-parent"]
    assert len(synthetic) == 1  # one synthetic root per affected trace
    assert synthetic[0]["args"]["synthetic_root"] is True
    marked = [
        e for e in events
        if e.get("args", {}).get("parent") == "evicted-parent"
    ]
    assert len(marked) == len(orphaned)


def test_never_allocated_parent_id_is_dangling():
    tr = Tracer(capacity=8)
    tr.record_span("bad", 0.0, 0.1, context=TraceContext(1, 999))
    [(rec, reason)] = tr.orphans()
    assert rec.name == "bad" and reason == "dangling"


# --- cross-thread propagation through the pipeline --------------------------


def test_trace_context_rides_pipeline_queue_block_policy():
    """One async batch's full chain — submit (producer thread) → pack/
    CSR merge (packer thread) → dispatch (producer thread again) —
    reconstructs as ONE tree from the ring, flow events included."""
    o = obs_pkg.Observability()
    eng = ArenaEngine(P, obs=o)
    eng.start_pipeline(capacity=4)  # block policy (default)
    w, l = make_matches(300, seed=1)
    eng.ingest_async(w, l)
    eng.flush()
    eng.shutdown()
    recs = o.tracer.spans()
    [root] = _by_name(recs, "batch.submit")
    [pack] = _by_name(recs, "pipeline.pack")
    [disp] = _by_name(recs, "pipeline.dispatch")
    [merge] = _by_name(recs, "ingest.csr_merge")
    assert root.parent_id == 0
    assert pack.trace_id == disp.trace_id == merge.trace_id == root.trace_id
    assert pack.parent_id == root.span_id
    assert disp.parent_id == root.span_id
    # The merge ran INSIDE the pack span, on the packer thread.
    assert merge.parent_id == pack.span_id
    assert merge.tid == pack.tid != root.tid
    # engine.apply nests under the dispatch.
    [apply_rec] = _by_name(recs, "engine.apply")
    assert apply_rec.parent_id == disp.span_id
    # The Chrome export draws flow arrows for the cross-thread edges.
    events = o.tracer.export_chrome_trace()
    flow_ids = {e["id"] for e in events if e.get("ph") in ("s", "f")}
    assert pack.span_id in flow_ids
    # Dangling-free at quiescence.
    assert [r for r, why in o.tracer.orphans() if why == "dangling"] == []


def test_dropped_batch_trace_ends_with_dropped_marker():
    """Drop-oldest shedding: the two dropped batches' traces END with
    an explicit `pipeline.dropped` span parented into their own
    `batch.submit` roots — and those traces never grew pack/dispatch
    spans. The surviving batches' traces completed normally."""
    o = obs_pkg.Observability()
    eng = ArenaEngine(P, obs=o)
    pipe = eng.start_pipeline(capacity=2, policy="drop-oldest")
    w, l = make_matches(100, seed=2)
    batches = [
        (w[i * 20:(i + 1) * 20], l[i * 20:(i + 1) * 20]) for i in range(5)
    ]
    with eng._store._lock:  # stall the packer inside its first merge
        eng.ingest_async(*batches[0])
        waited = 0
        while not pipe._packing and waited < 2000:
            waited += 1
            threading.Event().wait(0.005)
        assert pipe._packing
        for batch in batches[1:]:
            eng.ingest_async(*batch)  # capacity 2: two oldest raw drop
    eng.flush()
    eng.shutdown()
    recs = o.tracer.spans()
    roots = _by_name(recs, "batch.submit")
    assert len(roots) == 5
    dropped = _by_name(recs, "pipeline.dropped")
    assert len(dropped) == 2
    dropped_traces = {r.trace_id for r in dropped}
    for marker in dropped:
        [root] = [r for r in roots if r.trace_id == marker.trace_id]
        assert marker.parent_id == root.span_id
        # A shed batch was never packed or dispatched: the marker is
        # the trace's TERMINAL span, not a detour.
        trace_names = {r.name for r in o.tracer.trace(marker.trace_id)}
        assert trace_names == {"batch.submit", "pipeline.dropped"}
    # The surviving batches packed and dispatched under their roots:
    # parent ids survive the queue under drop-oldest exactly as under
    # block.
    for r in _by_name(recs, "pipeline.pack") + _by_name(
        recs, "pipeline.dispatch"
    ):
        assert r.trace_id not in dropped_traces
        [root] = [x for x in roots if x.trace_id == r.trace_id]
        assert r.parent_id == root.span_id
    assert len(_by_name(recs, "pipeline.pack")) == 3
    assert [r for r, why in o.tracer.orphans() if why == "dangling"] == []


def test_producer_label_defaults_local_and_is_overridable():
    o = obs_pkg.Observability()
    eng = ArenaEngine(P, obs=o)
    eng.start_pipeline(capacity=4)
    w, l = make_matches(60, seed=3)
    eng.ingest_async(w, l)
    eng.flush()
    eng.shutdown()
    reg = o.registry
    assert reg.counter(
        "arena_pipeline_submitted_batches_total", producer="local"
    ).value == 1
    assert reg.gauge(
        "arena_pipeline_queue_depth", producer="local"
    ).value >= 0.0
    # An explicit producer label lands on the SAME metric names.
    eng2 = ArenaEngine(P, obs=o)
    eng2.start_pipeline(capacity=4, producer="frontend-7")
    eng2.ingest_async(w, l)
    eng2.flush()
    eng2.shutdown()
    assert reg.counter(
        "arena_pipeline_submitted_batches_total", producer="frontend-7"
    ).value == 1
    assert reg.counter_sum("arena_pipeline_submitted_batches_total") == 2
    # Queue-depth samples reached the flight-recorder event log too.
    assert any(e["kind"] == "queue_depth" for e in o.events)


# --- exemplars --------------------------------------------------------------


def test_exemplar_lands_in_recorded_values_bucket():
    """A traced record stores its (trace_id, value) exemplar IN THE
    VALUE'S OWN BUCKET — `exemplar(q)` then answers "the trace behind
    that quantile". The mutation audit carries a wrong-bucket mutant;
    this is its named kill."""
    h = Histogram("t", {}, base=1e-3, num_buckets=8)
    v = 1e-3 * 2.0**3  # exactly on bound 3 -> bucket 3 (le semantics)
    h.record(v, trace_id=77)
    assert h.exemplars() == [(3, 77, v)]
    ex = h.exemplar(0.5)  # the only observation: quantile bucket is 3
    assert ex == {"trace_id": 77, "value": v, "bucket_index": 3}
    # Untraced records store nothing; empty buckets answer None.
    h2 = Histogram("t2", {}, base=1e-3, num_buckets=8)
    h2.record(v)
    assert h2.exemplars() == [] and h2.exemplar(0.5) is None
    # The snapshot and render expose the exemplar alongside the bucket.
    snap = h.snapshot()
    assert snap["exemplars"] == {"0.008": {"trace_id": 77, "value": v}}
    reg = Registry()
    reg._metrics[("t", ())] = h
    assert '# {trace_id="77"}' in reg.render()


def test_exemplars_stay_bucket_consistent_under_concurrent_observes():
    """N threads hammering one histogram with traced values: counts
    stay exact AND every stored exemplar's value belongs to the bucket
    it sits in (no torn trace/value pair can cross buckets)."""
    h = Histogram("lat", {}, base=1.0, num_buckets=16)
    threads, per_thread = 8, 500

    def worker(tid):
        for i in range(per_thread):
            v = float(2 ** (i % 10)) * (1.0 + 0.25 * (tid % 3))
            h.record(v, trace_id=tid * 100_000 + i + 1)

    workers = [
        threading.Thread(target=worker, args=(t,)) for t in range(threads)
    ]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=60.0)
    assert h.count == threads * per_thread
    exs = h.exemplars()
    assert exs, "traced records must leave exemplars"
    for bucket_idx, trace_id, value in exs:
        assert h.bucket_index(value) == bucket_idx
        assert trace_id > 0


# --- the acceptance criterion, tier-1-sized ---------------------------------


def test_mini_soak_all_spans_reachable_and_p99_exemplar_resolves(tmp_path):
    """A mixed workload (async ingest + queries + snapshot): every
    recorded span is reachable from a root via kept parents (zero
    orphans modulo the explicit evicted-parent/dropped markers — none
    of either here, the ring is large), and the p99 query-latency
    bucket's exemplar trace id resolves to a real recorded trace whose
    root is a `serve.query` span."""
    o = obs_pkg.Observability()
    srv = ArenaServer(num_players=P, max_staleness_matches=0, obs=o)
    eng = srv.engine
    w, l = make_matches(2600, seed=4)
    eng.ingest(w[:1000], l[:1000])
    eng.start_pipeline(capacity=4)
    for i in range(8):
        s = 1000 + i * 200
        eng.ingest_async(w[s:s + 200], l[s:s + 200])
        srv.query(leaderboard=(0, 5), players=[0, 1], pairs=[(0, 1)])
    eng.flush()
    srv.snapshot(tmp_path / "snap")
    srv.query(leaderboard=(0, 5))
    eng.shutdown()
    recs = o.tracer.spans()
    assert recs and all(r.trace_id > 0 for r in recs)
    # Zero orphans of EITHER kind: the ring held everything, so every
    # parent chain walks up to a root inside the ring.
    assert o.tracer.orphans() == []
    by_id = {r.span_id: r for r in recs}
    root_names = set()
    for r in recs:
        cur, hops = r, 0
        while cur.parent_id:
            cur = by_id[cur.parent_id]
            hops += 1
            assert hops <= len(recs), "parent cycle"
        root_names.add(cur.name)
        assert cur.trace_id == r.trace_id  # chains never cross traces
    # Every root is an intentional request/operation entry point.
    assert root_names <= {
        "batch.submit", "batch.ingest", "batch.update", "serve.query",
        "serve.snapshot", "serve.view_build",
    }
    assert {"batch.submit", "batch.ingest", "serve.query"} <= root_names
    # The p99 exemplar: a real trace id, resolving to a real query
    # trace (its root is the serve.query span that recorded it).
    h = o.registry.histogram("arena_query_latency_seconds")
    ex = h.exemplar(0.99)
    assert ex is not None and ex["trace_id"] > 0
    trace = o.tracer.trace(ex["trace_id"])
    assert trace, "exemplar trace id must resolve to recorded spans"
    assert any(r.name == "serve.query" and r.parent_id == 0 for r in trace)


# --- the flight recorder ----------------------------------------------------


def test_debug_bundle_contains_registry_dump(tmp_path):
    """The bundle carries ALL four evidence files; metrics.json is the
    full registry dump (the audit carries an omits-registry-dump
    mutant; this is its named kill), trace.json the Chrome export, and
    events.json the recent events with the queue-depth timeline."""
    o = obs_pkg.Observability()
    o.counter("arena_test_total", policy="block").inc(5)
    o.histogram("arena_test_seconds").record(0.25)
    with o.span("work"):
        pass
    o.event("queue_depth", depth=3, producer="local")
    o.event("drop", policy="drop-oldest", producer="local", batches=1,
            matches=20)
    path = dump_debug_bundle(o, tmp_path / "bundle",
                             config={"mode": "test", "seed": 0})
    assert path == tmp_path / "bundle"
    manifest = json.loads((path / "MANIFEST.json").read_text())
    assert set(manifest["files"]) == {
        "trace.json", "metrics.json", "config.json", "events.json",
        "profile.txt", "lint.sarif",
    }
    assert manifest["spans_recorded"] == 1
    # The v5 addition: the tree's lint surface at failure time, as one
    # SARIF document (suppressed findings included, so the bundle shows
    # the suppressions too, not just the clean verdict).
    sarif = json.loads((path / "lint.sarif").read_text())
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["tool"]["driver"]["name"] == "jaxlint"
    metrics = json.loads((path / "metrics.json").read_text())
    assert metrics["counters"]['arena_test_total{policy="block"}'] == 5
    assert metrics["histograms"]["arena_test_seconds"]["count"] == 1
    trace = json.loads((path / "trace.json").read_text())
    assert [e["name"] for e in trace["traceEvents"]] == ["work"]
    config = json.loads((path / "config.json").read_text())
    assert config == {"mode": "test", "seed": 0}
    events = json.loads((path / "events.json").read_text())
    assert len(events["events"]) == 2
    assert events["queue_depth_timeline"] == [
        [events["events"][0]["t"], 3]
    ]


def test_debug_bundle_write_is_atomic_and_replaces(tmp_path):
    """No .tmp residue after a dump; a second dump REPLACES the bundle
    whole (newer evidence, never a mix of two flights)."""
    o = obs_pkg.Observability()
    o.counter("a_total").inc()
    target = tmp_path / "bundle"
    dump_debug_bundle(o, target)
    assert not (tmp_path / "bundle.tmp").exists()
    o.counter("a_total").inc()
    dump_debug_bundle(o, target)
    assert not (tmp_path / "bundle.tmp").exists()
    metrics = json.loads((target / "metrics.json").read_text())
    assert metrics["counters"]["a_total"] == 2
