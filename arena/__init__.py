"""arena — JAX-native pairwise-comparison rating engine.

The first real compute subsystem of this repo (forward-building per
ROADMAP.md; the empty upstream reference defines nothing to reproduce —
see README.md "Arena engine" for the honesty framing).

Modules:
- `arena.ratings`  — pure vectorized math: batched online Elo,
  Bradley–Terry MLE, the scatter-free sorted segment sum.
- `arena.engine`   — ingestion (CSR-style grouping), shape-bucketed
  batching, the stateful `ArenaEngine` with jitted donated updates.
- `arena.ingest`   — incremental ingestion: the mergeable whole-set
  CSR grouping (delta-sorted tail + galloping merge, LSM-style
  size-ratio compaction), double-buffered reusable staging slots, and
  the chunked epoch layout for BT refits.
- `arena.pipeline` — overlapped ingest: the background packing thread
  behind a bounded queue (`ArenaEngine.ingest_async`/`flush`), with
  block / drop-oldest backpressure and a lossless drain protocol.
- `arena.serving`  — the serving surface: durable snapshot/restore of
  the whole engine (versioned on-disk format, `SnapshotError` reject
  posture), batched queries from immutable staleness-bounded views,
  production-mode sanitizer counters.
- `arena.net`     — the network serving tier: the HTTP/JSON wire layer
  (stdlib `ThreadingHTTPServer`; every response carries the staleness
  watermark + the request's trace id), the multi-producer front door
  (global sequence numbers at admission, merge strictly in sequence
  order — async==sync bit-exact under N writers), and the
  bounded-degradation load-shedding policy (shed batches coalesce
  into a summary update; backlog beyond the staleness bound is
  dropped COUNTED, never silently).
- `arena.obs`     — zero-dependency observability: thread-safe metrics
  registry (counters/gauges/log2 histograms, Prometheus `render()`,
  one-JSON-line `dump()`, `NullRegistry` no-op twin) and span tracing
  into a bounded ring with Chrome trace-event export. Every subsystem
  above reports through it; `ArenaEngine` defaults to the no-op
  instance, `ArenaServer` to a live one.
- `arena.sharding` — device mesh, partition-rule matching, shard_map
  data-parallel updates (CPU-mesh testable, no TPU required).
- `arena.baseline` — the deliberately naive loop implementation the
  bench measures against.
- `arena.bench_arena` — the one-JSON-line benchmark entrypoint.
"""

from arena.engine import ArenaEngine, bucket_size, pack_batch, pack_epoch
from arena.ingest import MergeableCSR, StagingBuffers, chunk_layout
from arena.net import ArenaHTTPServer, FrontDoor, FrontDoorError, WireClient
from arena.obs import NullRegistry, Observability, Registry, Tracer
from arena.pipeline import IngestPipeline, PipelineError
from arena.ratings import (
    bootstrap_intervals,
    bt_fit,
    bt_fit_chunked,
    elo_batch_update,
    elo_batch_update_sorted,
    elo_bootstrap,
    elo_epoch,
    elo_expected,
    sorted_segment_sum,
    sorted_segment_sum_chunked,
)
from arena.serving import ArenaServer, ServingView, SnapshotError

__all__ = [
    "ArenaEngine",
    "ArenaHTTPServer",
    "ArenaServer",
    "FrontDoor",
    "FrontDoorError",
    "IngestPipeline",
    "WireClient",
    "MergeableCSR",
    "NullRegistry",
    "Observability",
    "PipelineError",
    "Registry",
    "Tracer",
    "ServingView",
    "SnapshotError",
    "StagingBuffers",
    "bucket_size",
    "chunk_layout",
    "pack_batch",
    "pack_epoch",
    "bootstrap_intervals",
    "bt_fit",
    "bt_fit_chunked",
    "elo_batch_update",
    "elo_batch_update_sorted",
    "elo_bootstrap",
    "elo_epoch",
    "elo_expected",
    "sorted_segment_sum",
    "sorted_segment_sum_chunked",
]
