"""Tests for bench.py — the repo's only driver-facing runtime surface.

The driver contract: ``python bench.py`` prints exactly ONE JSON line on
stdout and exits 0, in every state the reference mount can be in (empty,
populated, missing, unreadable, or going stale mid-scan). There is no
reference workload to benchmark (the reference tree is empty — see
SURVEY.md / NON_GRAFTABLE.md), so these tests check honesty and
robustness of the reporting, not performance.
"""

import json
import os
import pathlib
import subprocess
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import bench  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parent.parent


def run_bench(reference_path):
    env = dict(os.environ)
    env["GRAFT_REFERENCE_PATH"] = str(reference_path)
    return subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        env=env,
        cwd="/tmp",  # must work from any cwd
    )


def assert_contract(proc):
    """Exactly one JSON line on stdout, rc 0, empty stderr."""
    assert proc.returncode == 0
    assert proc.stderr == ""
    lines = proc.stdout.splitlines()
    assert len(lines) == 1
    assert proc.stdout.endswith("\n")
    result = json.loads(lines[0])
    assert set(result) == {"metric", "value", "unit", "vs_baseline"}
    assert result["unit"] == "reference_entries"
    assert result["vs_baseline"] is None
    return result


def test_empty_reference(tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    result = assert_contract(run_bench(empty))
    assert result["metric"] == "non_graftable_reference_is_empty"
    assert result["value"] == 0


def test_populated_reference(tmp_path):
    """A re-mounted non-empty reference must surface a non-zero count."""
    populated = tmp_path / "populated"
    (populated / "src").mkdir(parents=True)
    (populated / "src" / "main.cu").write_text("// not empty\n")
    (populated / "README.md").write_text("hello\n")
    result = assert_contract(run_bench(populated))
    assert result["metric"] == "non_graftable_reference_is_empty"
    assert result["value"] == 3  # src/, src/main.cu, README.md


def test_missing_reference(tmp_path):
    result = assert_contract(run_bench(tmp_path / "does-not-exist"))
    assert result["metric"] == "reference_mount_missing_or_unreadable"
    assert result["value"] == -1


def test_reference_is_not_a_directory(tmp_path):
    not_a_dir = tmp_path / "file"
    not_a_dir.write_text("x")
    result = assert_contract(run_bench(not_a_dir))
    assert result["metric"] == "reference_mount_missing_or_unreadable"
    assert result["value"] == -1


def test_unreadable_reference(tmp_path):
    locked = tmp_path / "locked"
    locked.mkdir()
    locked.chmod(0o000)
    try:
        if os.access(locked, os.R_OK | os.X_OK):
            # Running as root: permission bits are bypassed, so this
            # state is unreachable here; the equivalent failure is
            # covered by test_scan_error_mid_iteration.
            pytest.skip("permission bits bypassed (root)")
        result = assert_contract(run_bench(locked))
        assert result["metric"] == "reference_mount_missing_or_unreadable"
        assert result["value"] == -1
    finally:
        locked.chmod(0o755)


def test_scan_error_mid_iteration(tmp_path, monkeypatch):
    """An OSError partway through the walk (stale mount, unreadable
    subtree) maps to a distinct metric instead of a traceback or a
    silent undercount. The failure is injected at the os.scandir layer
    that the real walk uses, so this exercises bench's actual error
    propagation — pathlib.rglob would have swallowed the error, which
    is why bench does not use it."""
    (tmp_path / "ok").mkdir()
    bad = tmp_path / "bad"
    bad.mkdir()
    real_scandir = os.scandir

    def flaky_scandir(path=".", *args, **kwargs):
        if pathlib.Path(path) == bad:
            raise OSError("mount went stale mid-iteration")
        return real_scandir(path, *args, **kwargs)

    monkeypatch.setattr(os, "scandir", flaky_scandir)
    result = bench.scan(tmp_path)
    assert result["metric"] == "reference_scan_error"
    assert result["value"] == -1


def test_stat_error_during_access_check(tmp_path, monkeypatch):
    """is_dir() itself raising OSError maps to missing_or_unreadable."""

    def broken_is_dir(self):
        raise OSError("stale file handle")

    monkeypatch.setattr(pathlib.Path, "is_dir", broken_is_dir)
    result = bench.scan(tmp_path)
    assert result["metric"] == "reference_mount_missing_or_unreadable"
    assert result["value"] == -1


def test_real_mount_contract():
    """Against the real configured mount, whatever its state, the driver
    contract holds and the metric is one of the three documented ones."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True,
        text=True,
        cwd="/tmp",
    )
    result = assert_contract(proc)
    assert result["metric"] in {
        "non_graftable_reference_is_empty",
        "reference_mount_missing_or_unreadable",
        "reference_scan_error",
    }
