"""Opt-in runtime sanitizers for the arena hot path.

The static rules in `arena.analysis.jaxlint` catch what is visible in
source; these catch what only shows up at runtime, and they make the
failure LOUD in tests instead of a silently wrong number in production:

- `checked()` — context manager wiring `jax_debug_nans` and
  `jax_debug_infs` on (restored on exit), so a NaN/Inf produced inside
  a rating update raises `FloatingPointError` at the op that made it.
- `RecompileSentinel` — snapshots jit-cache sizes after warmup and
  asserts zero new compiles afterwards. This is the runtime half of the
  engine's pow2 shape-bucket contract: arena traffic with arbitrary
  batch sizes must NEVER grow the jit cache past the buckets it
  touched during warmup.
- `donation_guard` — wraps a donating jitted callable and explicitly
  deletes the donated argument buffers after every call. When donation
  works (CPU/TPU honoring donate_argnums) this is a no-op; when it
  silently does NOT (shape/dtype mismatch makes XLA skip donation with
  only a warning), reuse of the stale buffer would return garbage-free
  but semantically-wrong results — the guard turns that reuse into an
  immediate `RuntimeError: Array has been deleted`.

Test posture raises; production posture counts: since the serving
layer, `RecompileSentinel(mode="count")` and
`donation_guard(mode="count", sample_every=N)` fold violations into
counters (`ArenaServer.stats()` exposes them) instead of raising —
a long-lived server wants the metric, not the crash. Defaults are
unchanged: tests still get the loud failure. Since the observability
layer (`arena/obs/`), the serving path watches BOTH jit caches — the
update fn and the engine's cached bootstrap resampler
(`num_bootstrap_compiles`) — and absorbs these counters into the
metrics registry (`arena_recompile_events_total`,
`arena_donation_*_total`), which is the schema the Prometheus
`render()`, `stats()`, and the soak bench's zero-recompile HARD gate
all read. The counters here stay the source; the registry is the
exposition path.

Everything here imports jax; the linter half of this package does not.
Keep it that way — lint must run on boxes with no accelerator stack.
"""

import functools
import threading
from contextlib import contextmanager

import jax

# The config knobs checked() owns. Values are read/restored via
# jax.config attributes (stable across the 0.4.x line pinned here).
_DEBUG_FLAGS = ("jax_debug_nans", "jax_debug_infs")


class SanitizerError(AssertionError):
    """Base class: a sanitizer invariant was violated."""


class RecompileError(SanitizerError):
    """The zero-new-compiles-after-warmup contract was broken."""


@contextmanager
def checked(debug_nans=True, debug_infs=True):
    """Run a block with NaN/Inf debugging on; restore flags on exit.

    Inside the block, any op producing a NaN (and, with `debug_infs`,
    an Inf) raises `FloatingPointError` immediately — eager or jitted.
    Note jitted functions compile a checked variant while the flag is
    on (the flag is part of the compilation context), so do not combine
    with a `RecompileSentinel` snapshot taken OUTSIDE the block.
    """
    old = {flag: getattr(jax.config, flag) for flag in _DEBUG_FLAGS}
    jax.config.update("jax_debug_nans", debug_nans)
    jax.config.update("jax_debug_infs", debug_infs)
    try:
        yield
    finally:
        for flag, value in old.items():
            jax.config.update(flag, value)


def _cache_count(watched) -> int:
    """Compile count of one watched object: a jitted callable (has
    `_cache_size`) or any zero-arg callable returning an int (e.g.
    `ArenaEngine.num_compiles`)."""
    cache_size = getattr(watched, "_cache_size", None)
    if cache_size is not None:
        return int(cache_size())
    if callable(watched):
        return int(watched())
    raise TypeError(
        f"cannot watch {watched!r}: need a jitted callable or a zero-arg "
        "compile-count callable"
    )


class RecompileSentinel:
    """Assert zero new XLA compiles between snapshot and check.

    Construction snapshots — so warm the watched functions up FIRST,
    then build the sentinel, then drive the traffic under test:

        eng = ArenaEngine(1000)
        eng.update(w, l)                      # warmup: compiles bucket
        sentinel = RecompileSentinel(update=eng.num_compiles)
        ... arbitrary batch sizes ...
        sentinel.assert_no_new_compiles()     # raises RecompileError

    Also usable as a context manager (`with RecompileSentinel(...)`):
    enter re-snapshots, exit checks.

    THREAD-AWARE since the overlapped ingest pipeline: jit caches are
    process-global, so a compile triggered from ANY thread (the
    pipeline's packer, a dispatching caller) moves the watched count
    and is caught by a sentinel built on a different thread. An
    internal lock makes snapshot()/new_compiles() atomic under
    concurrent callers; for a deterministic verdict, check at a
    quiescent point (after `ArenaEngine.flush()` has drained the
    pipeline), otherwise an in-flight compile may land on either side
    of the snapshot.

    PRODUCTION (metrics) MODE since the serving layer:
    `RecompileSentinel(mode="count", ...)` never raises — `observe()`
    folds any cache growth into the `recompile_events` counter and
    re-snapshots, so a long-lived server surfaces recompiles as a
    metric (`ArenaServer.stats()`) instead of a crashed request.
    `assert_no_new_compiles` delegates to `observe()` in count mode.
    The default mode stays "raise": the test posture is unchanged.
    """

    MODES = ("raise", "count")

    def __init__(self, mode="raise", **watched):
        if mode not in self.MODES:
            raise ValueError(
                f"unknown sentinel mode {mode!r}; pick one of {self.MODES}"
            )
        if not watched:
            raise ValueError("nothing to watch")
        self.mode = mode
        self.recompile_events = 0
        self._watched = watched
        self._lock = threading.Lock()
        self.snapshot()

    def snapshot(self):
        with self._lock:
            self._baseline = {k: _cache_count(v) for k, v in self._watched.items()}

    def new_compiles(self) -> dict:
        """name -> (baseline, now) for every watched fn that recompiled."""
        with self._lock:
            out = {}
            for name, obj in self._watched.items():
                now = _cache_count(obj)
                before = self._baseline[name]
                if now != before:
                    out[name] = (before, now)
            return out

    def observe(self) -> dict:
        """Fold cache growth into `recompile_events` and re-baseline.

        Atomic read-count-resnapshot, so concurrent observers never
        double-count one compile. Returns the growth dict (empty when
        nothing compiled) in every mode — this is the metrics-mode
        read path, but raise-mode callers may use it for logging too.
        """
        with self._lock:
            grew = {}
            for name, obj in self._watched.items():
                now = _cache_count(obj)
                before = self._baseline[name]
                if now != before:
                    grew[name] = (before, now)
                    self.recompile_events += now - before
                    self._baseline[name] = now
            return grew

    def assert_no_new_compiles(self):
        if self.mode == "count":
            self.observe()
            return
        grew = self.new_compiles()
        if grew:
            detail = ", ".join(
                f"{name}: {before} -> {now} compiles"
                for name, (before, now) in grew.items()
            )
            raise RecompileError(
                f"jit cache grew after warmup ({detail}); the shape-bucket "
                "contract promises zero recompiles — an unbucketed shape or "
                "dtype is leaking into a jitted signature"
            )

    def __enter__(self):
        self.snapshot()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.assert_no_new_compiles()
        return False


def donation_guard(fn, donate_argnums=(0,), mode="raise", sample_every=1):
    """Wrap a donating callable so reuse-after-donate fails loudly.

    mode="raise" (default, test posture): after every call, each
    positional argument named in `donate_argnums` that is a live
    `jax.Array` is explicitly deleted. If the wrapped function's own
    donation already consumed the buffer (the healthy case) this does
    nothing; if donation was silently skipped, the buffer dies here
    instead of lingering as a stale alias — and any later use raises
    `RuntimeError: Array has been deleted`.

    mode="count" (production/serving posture): every `sample_every`-th
    call, the guard only OBSERVES — a donated argument that survived
    the call (XLA skipped donation with nothing but a warning) bumps
    `guarded.donation_skipped` instead of being deleted, so a live
    server keeps serving and the skip shows up in metrics
    (`ArenaServer.stats()`), not as a mid-request crash. Sampling
    keeps the is_deleted() probes off most of the hot path.
    Counters on the wrapper: `calls`, `sampled`, `donation_skipped`.

    The wrapper passes through the wrapped jit's `_cache_size` (when
    present), so `ArenaEngine.num_compiles` and `RecompileSentinel`
    keep working on a guarded update function.
    """
    if mode not in ("raise", "count"):
        raise ValueError(f"unknown donation_guard mode {mode!r}")
    if sample_every < 1:
        raise ValueError(f"sample_every must be >= 1, got {sample_every}")

    @functools.wraps(fn)
    def guarded(*args, **kwargs):
        guarded.calls += 1
        out = fn(*args, **kwargs)
        if mode == "count" and guarded.calls % sample_every:
            return out
        for i in donate_argnums:
            if i >= len(args):
                continue
            arg = args[i]
            if not isinstance(arg, jax.Array):
                continue
            if mode == "count":
                if not arg.is_deleted():
                    guarded.donation_skipped += 1
            elif not arg.is_deleted():
                arg.delete()
        if mode == "count":
            guarded.sampled += 1
        return out

    guarded.calls = 0
    guarded.sampled = 0
    guarded.donation_skipped = 0
    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is not None:
        guarded._cache_size = cache_size
    return guarded
