"""Vectorized pairwise-comparison rating math (online Elo + Bradley–Terry).

This is the repo's first real compute subsystem (forward-building per
ROADMAP.md — NOT a reproduction of the empty reference; see README.md
"Arena engine"). Everything here is a pure function over JAX arrays so
it composes with `jax.jit`, `jax.lax.scan`, and `shard_map` without
hidden state.

Batch-update semantics
----------------------
Matches are processed in batches (rounds): every expected score in a
batch is computed from the ratings AT BATCH START, and the resulting
deltas are scatter-added together. Within a batch the update is
therefore order-free — `test_arena_ratings.py` pins permutation
invariance — and across batches it reduces to classic sequential Elo as
the batch size shrinks to 1. This is the standard formulation for
arena-style traffic where thousands of outcomes land between rating
refreshes. `arena/baseline.py` implements the SAME semantics as a
deliberately naive per-match loop, so the two paths are numerically
comparable (the bench asserts agreement before reporting a speedup).

The scatter-free hot path
-------------------------
`jax.ops.segment_sum` lowers to an XLA scatter, which is serialized on
CPU (~45ns/element measured on this image: 9ms for one 100k-match
batch — the entire hot path's budget). `sorted_segment_sum` is the
same reduction expressed scatter-free: gather the addends into
segment-sorted order through a precomputed permutation, one cumulative
sum, then differences at precomputed segment boundaries — ~25x faster
here, identical semantics (pinned against `segment_sum` in tests). The
permutation/boundaries depend only on the match INDICES, not on
ratings, so ingestion computes them once (cheap NumPy counting sort,
`arena/engine.py`) and every subsequent update — all Elo epochs, all
Bradley–Terry iterations — reuses them with zero scatters.

Float32 note: the cumulative sum runs in the ratings dtype (float32 by
default). Per 8k-match batch the worst-case rounding is ~1e-2 rating
points on a 1500-point scale — orders of magnitude below the k-factor;
the equivalence tests budget for it explicitly.
"""

import math
from functools import partial

import jax
import jax.numpy as jnp

# Classic Elo constants; every public function takes them as keyword
# overrides so engines can be configured without global state.
DEFAULT_K = 32.0
DEFAULT_SCALE = 400.0
DEFAULT_BASE = 1500.0
_LN10 = math.log(10.0)


def elo_expected(r_winner, r_loser, scale=DEFAULT_SCALE):  # deterministic
    """P(winner beats loser) under Elo: 1 / (1 + 10^((rl - rw)/scale)).

    Written as a sigmoid — 10^x == exp(x·ln10) exactly — because XLA's
    CPU `pow` is ~20x slower than `exp` (measured: 2.7ms vs 0.13ms per
    100k matches) and `jax.nn.sigmoid` is the numerically-stable fused
    form of 1/(1+exp(-x)).
    """
    return jax.nn.sigmoid((r_winner - r_loser) * (_LN10 / scale))


def elo_deltas(ratings, winners, losers, valid=None, k=DEFAULT_K, scale=DEFAULT_SCALE):  # deterministic
    """Per-match rating delta earned by each winner (loser gets -delta).

    `valid` is an optional 0/1 mask for padded batch slots (shape-
    bucketed batching pads variable-size batches up to a fixed bucket;
    a padded slot must contribute exactly zero).
    """
    d = k * (1.0 - elo_expected(ratings[winners], ratings[losers], scale))
    if valid is not None:
        d = d * valid
    return d


def elo_batch_update(  # deterministic
    ratings, winners, losers, valid=None, k=DEFAULT_K, scale=DEFAULT_SCALE
):
    """One batched Elo round via `jax.ops.segment_sum` scatter-add.

    The straightforward formulation: kept as the reference/simple path
    (and the one `arena/sharding.py` distributes, where each device
    scatters only its shard). The engine's hot path is
    `elo_batch_update_sorted`.
    """
    d = elo_deltas(ratings, winners, losers, valid, k, scale)
    signed = jnp.concatenate([d, -d])
    idx = jnp.concatenate([winners, losers])
    return ratings + jax.ops.segment_sum(
        signed, idx, num_segments=ratings.shape[0]
    )


def sorted_segment_sum(values, perm, bounds):  # deterministic
    """Scatter-free segment sum over a precomputed grouping.

    `perm` permutes `values` into segment-sorted order; `bounds[s]` is
    the start offset of segment s in that order (length num_segments+1,
    monotone, bounds[-1] == len(values)). Returns per-segment sums —
    exactly `jax.ops.segment_sum(values, ids, num_segments)` for the
    `ids` the grouping was built from (property-tested).
    """
    cs = jnp.concatenate(
        [jnp.zeros((1,), values.dtype), jnp.cumsum(values[perm])]
    )
    return cs[bounds[1:]] - cs[bounds[:-1]]


def elo_batch_update_sorted(  # deterministic
    ratings, winners, losers, valid, perm, bounds, k=DEFAULT_K, scale=DEFAULT_SCALE
):
    """One batched Elo round on the scatter-free hot path.

    `perm`/`bounds` group the concatenated [winners, losers] index
    array by player (built once at ingest — `engine.pack_batch`). The
    signed addend array is [d, -d] in match order, so `perm` must have
    been computed over that same concatenation.
    """
    d = elo_deltas(ratings, winners, losers, valid, k, scale)
    signed = jnp.concatenate([d, -d])
    return ratings + sorted_segment_sum(signed, perm, bounds)


def tenant_sorted_segment_sum(values, perm, bounds):  # deterministic
    """Row-parallel `sorted_segment_sum`: one tenant per row.

    `values` is (T, 2B) signed addends in match order, `perm` a (T, 2B)
    per-row grouping permutation, `bounds` (T, P+1) per-row segment
    starts. Each row's arithmetic — gather, cumsum along axis 1,
    boundary differences — is the EXACT op sequence the 1-D kernel
    runs on a (2B,) batch, so every tenant's segment sums are
    bit-identical to a dedicated single-tenant dispatch over the same
    padded layout (property-tested; the tenant bench hard-gates it).
    One fused call replaces T Python dispatches — tenant is just one
    more leading axis, the same trick the chunked BT path plays with
    its chunk axis.
    """
    sv = jnp.take_along_axis(values, perm, axis=1)
    cs = jnp.concatenate(
        [jnp.zeros((values.shape[0], 1), values.dtype),
         jnp.cumsum(sv, axis=1)],
        axis=1,
    )
    return (
        jnp.take_along_axis(cs, bounds[:, 1:], axis=1)
        - jnp.take_along_axis(cs, bounds[:, :-1], axis=1)
    )


def elo_tenant_update_sorted(  # deterministic
    ratings, winners, losers, valid, perm, bounds, k=DEFAULT_K, scale=DEFAULT_SCALE
):
    """One batched Elo round for EVERY tenant in one fused dispatch.

    `ratings` is (T, P) — tenant-major, the multi-tenant engine's
    native state. winners/losers/valid are (T, B) with tenant-LOCAL
    player ids; perm (T, 2B) and bounds (T, P+1) are per-row groupings
    over each row's concatenated [winners, losers] (built host-side in
    `tenancy.pack_tenant_batch`). A tenant whose row is all padding
    (valid == 0 everywhere) contributes signed zeros only, and
    ``x + (±0.0) == x`` bitwise for every rating the engine can hold —
    so idle tenants ride along for free, bit-exactly.
    """
    r_w = jnp.take_along_axis(ratings, winners, axis=1)
    r_l = jnp.take_along_axis(ratings, losers, axis=1)
    d = k * (1.0 - elo_expected(r_w, r_l, scale)) * valid
    signed = jnp.concatenate([d, -d], axis=1)
    return ratings + tenant_sorted_segment_sum(signed, perm, bounds)


def elo_epoch(  # deterministic
    ratings, winners, losers, valid, perms, bounds, k=DEFAULT_K, scale=DEFAULT_SCALE
):
    """A full pass over pre-bucketed batches, fused into ONE computation.

    All arguments are stacked per-batch: winners/losers/valid are
    (num_batches, B), perms (num_batches, 2B), bounds
    (num_batches, P+1). `lax.scan` keeps the whole epoch inside a
    single XLA executable — per-dispatch overhead (~1ms on this
    1-core image, larger than the compute itself) is paid once per
    epoch instead of once per batch.
    """

    def step(r, batch):
        w, l, v, p, b = batch
        return elo_batch_update_sorted(r, w, l, v, p, b, k, scale), None

    ratings, _ = jax.lax.scan(step, ratings, (winners, losers, valid, perms, bounds))
    return ratings


# --- Bradley–Terry maximum likelihood -------------------------------------
#
# Model: P(i beats j) = p_i / (p_i + p_j) with strengths p > 0. Fitted
# by Hunter's (2004) minorize-maximize iteration:
#
#     p_i <- (W_i + prior) / (sum_{matches m touching i} 1/(p_w(m)+p_l(m))
#             + 2*prior/(p_i + 1))
#
# where W_i is i's total win count. The per-player denominator sum is a
# segment sum over the SAME concatenated [winners, losers] grouping the
# Elo path uses, so one ingest serves both models. `prior` adds a
# virtual win and loss against a ghost player of strength 1 —
# without it an undefeated player's MLE diverges to infinity.
# Strengths are renormalized to unit geometric mean each step (the
# likelihood is scale-invariant; pinning the gauge keeps iterates
# comparable and finite).


def bt_mm_step(strengths, winners, losers, valid, perm, bounds, win_counts, prior):  # deterministic
    """One Bradley–Terry MM update over all matches (vectorized)."""
    s = strengths[winners] + strengths[losers]
    inv = valid / s
    denom = sorted_segment_sum(jnp.concatenate([inv, inv]), perm, bounds)
    denom = denom + 2.0 * prior / (strengths + 1.0)
    new = (win_counts + prior) / denom
    # Gauge fix: unit geometric mean.
    new = new * jnp.exp(-jnp.mean(jnp.log(new)))
    return new


def bt_fit(  # deterministic
    num_players,
    winners,
    losers,
    valid,
    perm,
    bounds,
    win_counts,
    num_iters=50,
    prior=0.1,
    dtype=jnp.float32,
):
    """Batched Bradley–Terry MLE: `num_iters` MM steps fused in one scan.

    Returns strengths with unit geometric mean; rank by descending
    strength. `num_iters` is static (part of the compiled shape), which
    is what lets the whole fit be one dispatch. Pure function — wrap it
    in `jax.jit` at the call site (see `jit_bt_fit`) or the scan runs
    eagerly, one dispatch per op.
    """
    init = jnp.ones((num_players,), dtype)

    def step(p, _):
        return bt_mm_step(p, winners, losers, valid, perm, bounds, win_counts, prior), None

    out, _ = jax.lax.scan(step, init, None, length=num_iters)
    return out


def sorted_segment_sum_chunked(values, perms, bounds):  # deterministic
    """Scatter-free segment sum over a CHUNKED grouping.

    The whole-set grouping split into fixed-size chunks over the
    sorted entry order (`arena.ingest.chunk_layout`): `perms` is
    (num_chunks, C) of positions into `values`, `bounds` is
    (num_chunks, P+1) per-chunk clipped segment offsets. `values` must
    carry ONE trailing zero sentinel (length E+1 for E real entries) —
    padded perm slots point at it, so no validity mask exists anywhere
    on this path. A `lax.scan` accumulates per-chunk partial segment
    sums; the largest live buffer is one chunk (C), never the
    2*pow2(N) single-bucket pad.
    """

    def step(acc, chunk):
        p, b = chunk
        cs = jnp.concatenate(
            [jnp.zeros((1,), values.dtype), jnp.cumsum(values[p])]
        )
        return acc + (cs[b[1:]] - cs[b[:-1]]), None

    init = jnp.zeros((bounds.shape[1] - 1,), values.dtype)
    out, _ = jax.lax.scan(step, init, (perms, bounds))
    return out


def bt_mm_step_chunked(strengths, winners, losers, perms, bounds, win_counts, prior):  # deterministic
    """One Bradley–Terry MM update via the chunked segment sum.

    Same update rule as `bt_mm_step`; the denominator accumulates
    chunk-by-chunk instead of through one bucket-wide cumsum. The
    winners/losers arrays are EXACT length (no pad matches): match i's
    two entries live at interleaved positions 2i (winner) and 2i+1
    (loser), both carrying 1/(p_w + p_l) — `jnp.repeat(inv, 2)` lays
    the values out in exactly that order.
    """
    inv = 1.0 / (strengths[winners] + strengths[losers])
    values = jnp.concatenate([jnp.repeat(inv, 2), jnp.zeros((1,), inv.dtype)])
    denom = sorted_segment_sum_chunked(values, perms, bounds)
    denom = denom + 2.0 * prior / (strengths + 1.0)
    new = (win_counts + prior) / denom
    return new * jnp.exp(-jnp.mean(jnp.log(new)))


def bt_fit_chunked(  # deterministic
    num_players,
    winners,
    losers,
    perms,
    bounds,
    win_counts,
    num_iters=50,
    prior=0.1,
    dtype=jnp.float32,
):
    """Bradley–Terry MLE over the chunked epoch layout: `num_iters` MM
    steps fused in one scan, peak bucket = one chunk instead of one
    pow2 pad of the whole set. Wrap in jit at the call site
    (`jit_bt_fit_chunked`)."""
    init = jnp.ones((num_players,), dtype)

    def step(p, _):
        return (
            bt_mm_step_chunked(p, winners, losers, perms, bounds, win_counts, prior),
            None,
        )

    out, _ = jax.lax.scan(step, init, None, length=num_iters)
    return out


def jit_bt_fit_chunked(num_players, num_iters=50, prior=0.1):
    """`bt_fit_chunked` compiled for a fixed player count / budget."""
    return jax.jit(
        partial(bt_fit_chunked, num_players, num_iters=num_iters, prior=prior)
    )


def bt_log_likelihood(strengths, winners, losers, valid=None):  # deterministic
    """Total log-likelihood of the observed outcomes (for tests: each
    MM step must not decrease it)."""
    ll = jnp.log(strengths[winners] / (strengths[winners] + strengths[losers]))
    if valid is not None:
        ll = ll * valid
    return jnp.sum(ll)


def jit_bt_fit(num_players, num_iters=50, prior=0.1):
    """`bt_fit` compiled for a fixed player count / iteration budget."""
    return jax.jit(
        partial(bt_fit, num_players, num_iters=num_iters, prior=prior)
    )


# --- bootstrap confidence intervals ----------------------------------------
#
# LMSYS-style rating uncertainty: resample the match set with
# replacement, replay the epoch, read the spread of the resampled
# ratings. The resample is a POISSON bootstrap — each match gets an
# independent Poisson(1) weight, equivalent in distribution to
# multinomial resampling for large N but expressible as a pure
# per-match multiply: the weight rides the SAME `valid` mask the
# padded slots already use, so every bootstrap round reuses the
# precomputed grouping (perm/bounds) with zero re-sorts and zero new
# layouts. N rounds vmap over a seeded key array into one executable;
# at the measured ~2ms per 100k-match epoch, 32 rounds are ~64ms of
# device time per interval refresh.


def elo_bootstrap(
    ratings0, winners, losers, valid, perms, bounds, keys,
    k=DEFAULT_K, scale=DEFAULT_SCALE,
):
    """Bootstrap rating samples: one resampled epoch per key.

    All epoch arguments are the stacked per-batch layout `elo_epoch`
    takes; `keys` is a (num_rounds, 2) jax PRNG key array (e.g.
    `jax.random.split(jax.random.PRNGKey(seed), num_rounds)`).
    Returns (num_rounds, num_players) ratings — deterministic for a
    fixed key array. Pure function; wrap in jit at the call site
    (`jit_elo_bootstrap`) or each round dispatches eagerly.
    """

    def one_round(key):
        weights = jax.random.poisson(key, 1.0, shape=valid.shape).astype(
            valid.dtype
        )
        return elo_epoch(
            ratings0, winners, losers, valid * weights, perms, bounds, k, scale
        )

    return jax.vmap(one_round)(keys)


def bootstrap_intervals(samples, alpha=0.05):
    """(lo, hi) percentile interval per player from bootstrap samples.

    `samples` is (num_rounds, num_players); returns two (num_players,)
    arrays at the alpha/2 and 1-alpha/2 quantiles (central 1-alpha
    interval, the standard percentile bootstrap).
    """
    lo = jnp.quantile(samples, alpha / 2.0, axis=0)
    hi = jnp.quantile(samples, 1.0 - alpha / 2.0, axis=0)
    return lo, hi


def jit_elo_bootstrap(k=DEFAULT_K, scale=DEFAULT_SCALE):
    """`elo_bootstrap` compiled for fixed constants. One executable per
    (num_batches, batch, num_rounds) shape triple — refresh intervals
    at a fixed cadence/shape to keep the cache flat."""
    return jax.jit(partial(elo_bootstrap, k=k, scale=scale))


def jit_elo_epoch(num_players, k=DEFAULT_K, scale=DEFAULT_SCALE, donate=True):
    """`elo_epoch` compiled with the ratings buffer donated.

    Donation lets XLA reuse the old ratings buffer for the new ratings
    (verified effective on CPU in tests: the donated input is deleted),
    which matters once num_players is large enough that the state is
    the dominant allocation.
    """
    fn = partial(elo_epoch, k=k, scale=scale)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())
