"""HTTP/JSON wire server: the network face of `ArenaServer`.

Stdlib only (no new dependencies), exposing the already-JSON-shaped
serving responses over these endpoints:

    GET  /healthz                     liveness + applied watermark
    GET  /leaderboard?offset=&limit=  one descending-rating page
    GET  /player/{id}                 one player's rating row (+ CI)
    GET  /h2h?a=&b=                   Elo P(a beats b)
    GET  /match?n=&tenant=&policy=    policy-ranked pairing proposals
    POST /query                       many lookups, ONE view (batched)
    POST /submit                      admit one batch at the front door
    GET  /stats                       the registry's Prometheus render()
    GET  /debug/window                sliding-window rates + quantiles
    GET  /debug/slo                   burn-rate evaluation, alert states
    GET  /debug/profile               sampled stacks by thread role
    GET  /debug/trace/{id}            one trace's spans, oldest first

The /debug family is the live ops plane (PR 13): the same envelope,
span, and counter treatment as every other endpoint (the audit's
debug-endpoint-omits-envelope mutant pins that), served from the
`Observability` the registry already lives in. `start()` starts the
ops-plane threads (window rotation + profiler sampling) next to the
front end; `close()` stops them.

One request reads ONE immutable `ServingView` (the `ArenaServer.query`
contract — the handler never touches engine internals), and every JSON
response carries the staleness ``watermark`` with the request's
``trace_id`` next to it (`arena.net.protocol.make_response`); `/stats`
is Prometheus text, so its pair rides the `X-Arena-Watermark` /
`X-Arena-Trace-Id` headers instead (all endpoints set both headers).

Each request runs under a `net.<endpoint>` root span, so the serving
spans it triggers (view build, query) — and, for `/submit`, the whole
cross-thread admission → merge → pack → dispatch chain — reconstruct
as one trace from the id in the response. Requests land in
`arena_http_requests_total{endpoint=,status=}` and the per-endpoint
latency histogram through the server's ONE registry (the same schema
`stats()`, `/stats`, and the frontend bench read).

**The fast wire path (PR 16).** `handle_request` is the one
transport-agnostic request core; two front ends drive it:

- the default `EventLoopFrontEnd` (`arena.net.fastpath`): a single
  `selectors` loop answers every read inline and hands only POST
  /submit to a small blocking pool (the front door's admission may
  block; its sequencing semantics are untouched);
- the legacy `ThreadingHTTPServer` (``fastpath_reads=False``): one
  daemon thread per connection, same core, same responses.

Reads on leaderboard/player/h2h are served from the watermark-keyed
byte cache (`ResponseCache`): rendered once per (endpoint, params,
view generation), invalidated structurally when the view changes, and
completed with each request's own trace id by a byte splice. Hot
leaderboard pages are prerendered into the cache at view-refresh time
through `ArenaServer.add_refresh_listener`. Which front end answered
is observable: /healthz reports ``front_end``.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from arena.net import fastpath, protocol
from arena.net import frontdoor as frontdoor_mod

# Submit responses are 202 (accepted into the total order, applied
# asynchronously) — the wire mirrors the front door's semantics.
STATUS_ACCEPTED = 202


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # The wire tier logs through the metrics registry, not stderr.
    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        return None

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def _handle(self, method):
        wire = self.server.wire
        # Drain the request body FIRST, unconditionally: on a keep-
        # alive connection an unread body would be parsed as the next
        # request's request line (every error path would poison the
        # connection behind it).
        length = int(self.headers.get("Content-Length") or 0)
        body_raw = self.rfile.read(length) if length else b""
        status, body, content_type, watermark, trace_id = wire.handle_request(
            method, self.path, body_raw
        )
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Arena-Watermark", str(watermark))
            self.send_header("X-Arena-Trace-Id", str(trace_id))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionError):
            pass  # client went away mid-response; already counted


def _dispatch(wire, endpoint, params, body_raw):
    """The non-cached endpoint switch: returns (status, payload) where
    payload None means the Prometheus text body."""
    srv = wire.server
    if endpoint == "healthz":
        return 200, _healthz_payload(wire)
    if endpoint == "stats":
        return 200, None  # body rendered from the registry
    if endpoint == "leaderboard":
        if "as_of" in params:
            return 200, _as_of_payload(wire, params)
        return 200, srv.query(
            leaderboard=(params["offset"], params["limit"]),
            tenant=params.get("tenant"),
        )
    if endpoint == "player":
        if "as_of" in params:
            return 200, _as_of_payload(wire, params)
        return 200, srv.query(
            players=[params["player"]], tenant=params.get("tenant")
        )
    if endpoint == "h2h":
        return 200, srv.query(
            pairs=[(params["a"], params["b"])],
            tenant=params.get("tenant"),
        )
    if endpoint == "query":
        return 200, srv.query_batch(protocol.parse_query_body(body_raw))
    if endpoint == "submit":
        return _submit(wire, body_raw)
    if endpoint == "log":
        return 200, _log_payload(wire, params)
    if endpoint == "match":
        return 200, _match_payload(wire, params)
    if endpoint == "debug_window":
        return 200, wire.obs.windows.read()
    if endpoint == "debug_slo":
        return 200, wire.obs.slo.evaluate()
    if endpoint == "debug_profile":
        return 200, wire.obs.profiler.snapshot()
    if endpoint == "debug_trace":
        return 200, _trace_payload(wire, params["trace_id"])
    raise protocol.ProtocolError(404, f"no such endpoint: {endpoint!r}")


def _match_payload(wire, params):
    """GET /match: the matchmaking plane. 503 when no `Matchmaker` is
    attached (read-only deployments serve everything else unchanged);
    the payload itself is rendered by
    `arena.match.render_match_payload` off one immutable view."""
    matchmaker = wire.matchmaker
    if matchmaker is None:
        raise protocol.ProtocolError(
            503, "this server has no matchmaker attached"
        )
    return matchmaker.propose_payload(
        params["n"], policy=params.get("policy"),
        tenant=params.get("tenant"),
    )


def _healthz_payload(wire):  # schema: wire-healthz@v1
    srv = wire.server
    return {
        "status": "ok",
        "front_end": wire.front_end,
        "matchmaker": wire.matchmaker is not None,
        "players": srv.engine.num_players,
        "matches_ingested": srv.engine.matches_ingested,
    }


def _trace_payload(wire, trace_id):  # schema: wire-debug-trace@v1
    """Resolve one trace id (a response's `trace_id`, an SLO
    alert's exemplar) into its recorded spans. 404 when the ring
    kept nothing for it — evicted or never allocated. The payload
    key is `queried_trace_id`: the envelope's own `trace_id` slot
    belongs to THIS request's trace, authoritatively."""
    spans = wire.obs.tracer.trace(trace_id)
    if not spans:
        raise protocol.ProtocolError(
            404, f"no spans recorded for trace {trace_id}"
        )
    return {
        "queried_trace_id": trace_id,
        "spans": [
            {
                "name": r.name,
                "start": r.start,
                "duration": r.duration,
                "tid": r.tid,
                "span_id": r.span_id,
                "parent_id": r.parent_id,
            }
            for r in spans
        ],
    }


def _submit(wire, body_raw):  # schema: wire-submit-response@v1
    frontdoor = wire.frontdoor
    if frontdoor is None:
        raise protocol.ProtocolError(
            503, "this server has no front door (read-only replica)"
        )
    winners, losers, producer, tenant, category = protocol.parse_submit_body(
        body_raw
    )
    if category is not None:
        if wire.categories is None:
            raise protocol.ProtocolError(
                400, "this server has no category registry: submit by "
                "'tenant' instead"
            )
        # Registry resolution is the category's wire sanitizer: an
        # unknown name is a ValueError -> 400, same reject posture as
        # an unknown tenant at admission.
        tenant = wire.categories.resolve(category)
    seq = frontdoor.submit(winners, losers, producer=producer, tenant=tenant)
    out = {
        "seq": seq,
        "producer": producer,
        "matches": int(winners.shape[0]),
        "pending_batches": frontdoor.pending_batches(),
    }
    if tenant is not None:
        out["tenant"] = int(tenant)
    return STATUS_ACCEPTED, out


def _log_payload(wire, params):  # schema: wire-log-segment@v1
    """One page of the writer's applied log for replica catch-up.
    Records ride in log-sequence order; `next_seq` is the cursor the
    replica passes back as `after_seq`, `log_len` the writer's current
    log length (the replica's lag in records is `log_len - next_seq`),
    and `base_watermark` the engine watermark the log started at."""
    frontdoor = wire.frontdoor
    if frontdoor is None:
        raise protocol.ProtocolError(
            503, "this server has no front door (read-only replicas "
            "ship no log)"
        )
    limit = params["limit"]
    if limit <= 0:
        limit = frontdoor_mod.MAX_LOG_SEGMENT_RECORDS
    try:
        records, next_seq, log_len, base_watermark = frontdoor.log_segment(
            after_seq=params["after_seq"],
            after_watermark=params["after_watermark"],
            limit=limit,
        )
    except frontdoor_mod.FrontDoorError as exc:
        raise protocol.ProtocolError(503, str(exc)) from None
    except ValueError as exc:
        # A watermark that is not a record boundary: the replica must
        # re-seat its cursor — a conflict, not a malformed request.
        raise protocol.ProtocolError(409, str(exc)) from None
    # The tenant column: log records carry COMPOSITE ids (what replicas
    # replay verbatim), so each record's tenant is derived, not stored —
    # the uniform tenant of its ids, or -1 for a record spanning several
    # (a shed summary coalesces every producer's backlog).
    ppt = wire.server.engine.players_per_tenant
    return {
        "records": [
            {
                "seq": seq,
                "kind": kind,
                "winners": w.tolist(),
                "losers": l.tolist(),
                "tenant": _record_tenant(w, l, ppt),
                "record_watermark": wm,
            }
            for seq, kind, w, l, wm in records
        ],
        "next_seq": next_seq,
        "log_len": log_len,
        "base_watermark": base_watermark,
    }


def _record_tenant(w, l, players_per_tenant):  # deterministic
    """The uniform tenant of one log record's composite ids (0 for an
    empty record, -1 for a multi-tenant summary)."""
    if not w.shape[0]:
        return 0
    tenants = np.concatenate([w, l]) // players_per_tenant
    t = int(tenants[0])
    return t if bool((tenants == t).all()) else -1


def _as_of_payload(wire, params):
    """Time-travel reads: `?as_of=<watermark>` answered by the
    configured `TimeTravelIndex` (nearest retained snapshot + shipped
    log replay), not the live view. The payload carries the HISTORICAL
    watermark, so the envelope is honest about which state answered."""
    if "tenant" in params:
        raise protocol.ProtocolError(
            400, "time-travel reads answer from the composite index; "
            "'tenant' and 'as_of' cannot be combined"
        )
    index = wire.time_travel
    if index is None:
        raise protocol.ProtocolError(
            503, "time travel is not configured on this server "
            "(no snapshot + log index)"
        )
    if "player" in params:
        return index.player(params["player"], params["as_of"])
    return index.leaderboard(params["offset"], params["limit"], params["as_of"])


class ArenaHTTPServer:  # protocol: start->close
    """The wire tier: one front end over one `ArenaServer` (+ optionally
    one `FrontDoor` for the submit path; without one the server is a
    read-only replica and /submit answers 503).

    ``fastpath_reads=True`` (the default) serves through the
    `selectors` event loop; ``False`` falls back to the legacy
    `ThreadingHTTPServer`. Both share `handle_request`, the byte
    cache, and every metric. ``cache_capacity=0`` disables the cache
    (every read renders fresh). `port=0` binds an ephemeral port
    (tests/bench); `self.port` is the bound one either way. `start()`
    serves on daemon threads; `close()` shuts down and joins. Usable
    as a context manager."""

    def __init__(self, server, frontdoor=None, host="127.0.0.1", port=0,
                 fastpath_reads=True,
                 cache_capacity=fastpath.DEFAULT_CACHE_CAPACITY,
                 prerender_pages=fastpath.DEFAULT_PRERENDER_PAGES,
                 submit_workers=fastpath.DEFAULT_SUBMIT_WORKERS,
                 time_travel=None, categories=None, matchmaker=None):
        self.server = server
        self.frontdoor = frontdoor
        # Optional `arena.match.Matchmaker`: the matchmaking plane
        # behind GET /match. Without one, /match answers 503.
        self.matchmaker = matchmaker
        # Optional `arena.tenancy.CategoryRegistry`: lets /submit name
        # a tenant by category ("coding", "creative-writing", ...) —
        # the LMSYS per-category slice use-case. Without one, category
        # submits answer 400.
        self.categories = categories
        # Optional `arena.net.replica.TimeTravelIndex` (duck-typed:
        # anything with leaderboard/player as-of renderers); without
        # one, `?as_of=` reads answer 503.
        self.time_travel = time_travel
        self.obs = server.obs
        self.cache = (
            fastpath.ResponseCache(self.obs, capacity=cache_capacity)
            if cache_capacity > 0
            else None
        )
        self._prerender_pages = tuple(prerender_pages)
        self._httpd = None
        self._loop = None
        if fastpath_reads:
            self._loop = fastpath.EventLoopFrontEnd(
                self, host=host, port=port, submit_workers=submit_workers
            )
            self.host, self.port = self._loop.host, self._loop.port
        else:
            self._httpd = ThreadingHTTPServer((host, port), _Handler)
            self._httpd.daemon_threads = True
            self._httpd.wire = self
            self.host, self.port = self._httpd.server_address[:2]
        self._thread = None
        if self.cache is not None:
            # Prerender hot leaderboard pages at every view refresh:
            # they change exactly once per refresh and everyone reads
            # them, so the bytes exist before the first reader misses.
            self.server.add_refresh_listener(self._prerender)

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    @property
    def front_end(self):
        """Which transport answers reads: "eventloop" (the selectors
        loop) or "threaded" (the legacy thread-per-connection server).
        /healthz reports this — a silent fallback is a test failure,
        not a deploy surprise."""
        return "eventloop" if self._loop is not None else "threaded"

    def render(self):
        """The /stats body: the registry's Prometheus exposition."""
        return self.obs.render()

    # --- the transport-agnostic request core -------------------------

    def handle_request(self, method, path, body_raw):
        """One wire request, whatever the transport: route, span,
        dispatch (through the byte cache for the cacheable GETs),
        envelope, count. Returns (status, body_bytes, content_type,
        watermark, trace_id) ready for framing.

        The envelope watermark is the payload's own view watermark
        when the payload carries one (query responses: the watermark
        of the ONE view that answered), else the engine's applied
        watermark (liveness/submit/error responses)."""
        obs = self.obs
        t0 = time.perf_counter()
        endpoint = "unmatched"
        trace_id = 0
        payload = None
        head = None
        watermark = None
        try:
            endpoint, params = protocol.parse_path(method, path)
            with obs.span(f"net.{endpoint}") as root:
                trace_id = root.trace_id
                if (
                    self.cache is not None
                    and endpoint in fastpath.CACHEABLE_ENDPOINTS
                    and "as_of" not in params
                ):
                    status, head, watermark = fastpath.serve_cached(
                        self, endpoint, params
                    )
                else:
                    status, payload = _dispatch(
                        self, endpoint, params, body_raw
                    )
        except protocol.ProtocolError as exc:
            status, payload, head = exc.status, {"error": str(exc)}, None
        except ValueError as exc:
            # The serving/admission reject posture (bad ids, malformed
            # arrays): the caller's fault, named, no state change.
            status, payload, head = 400, {"error": str(exc)}, None
        except Exception as exc:  # noqa: BLE001 — a handler crash must
            # degrade to a structured 500, never a dropped connection.
            status, payload = 500, {"error": f"{type(exc).__name__}: {exc}"}
            head = None
        if watermark is None:
            if payload is not None and "watermark" in payload:
                watermark = payload["watermark"]
            else:
                watermark = self.server.engine.matches_applied
        if head is not None:
            body = fastpath.complete_response(head, trace_id)
            content_type = "application/json"
        elif payload is None:  # /stats: Prometheus text, envelope in headers
            body = self.render().encode("utf-8")
            content_type = "text/plain; version=0.0.4"
        else:
            body = json.dumps(
                protocol.make_response(
                    payload, watermark=watermark, trace_id=trace_id
                )
            ).encode("utf-8")
            content_type = "application/json"
        obs.counter(
            "arena_http_requests_total", endpoint=endpoint, status=str(status)
        ).inc()
        obs.histogram(
            "arena_http_request_latency_seconds", endpoint=endpoint
        ).record(time.perf_counter() - t0, trace_id=trace_id)
        return status, body, content_type, watermark, trace_id

    # --- cache plumbing ----------------------------------------------

    def _prerender(self, view):
        """View-refresh listener: rebuild the hot leaderboard pages'
        bytes for the fresh view. Runs under the serving lock, so the
        pages are in the cache before the refresh is observable."""
        srv = self.server
        staleness = view.matches_ingested - view.watermark
        for offset, limit in self._prerender_pages:
            params = {"offset": offset, "limit": limit}
            payload = fastpath.render_query_payload(
                srv, view, False, "leaderboard", params, staleness=staleness
            )
            head = fastpath.render_head(payload, view.watermark)
            self.cache.put(
                fastpath.cache_key("leaderboard", params), view.seq, head,
                prerendered=True,
            )

    def verify_cache_consistency(self):
        """The cache-consistency hard gate (the frontend bench raises
        on failure): every cached entry of the current view generation
        must byte-equal a fresh render. Returns (checked, mismatches)."""
        if self.cache is None:
            return 0, []
        return fastpath.verify_cache_consistency(self)

    # --- lifecycle ---------------------------------------------------

    def start(self):
        if self._started():
            raise RuntimeError("wire server already started")
        # The ops plane serves live at /debug/*: rotation + sampling
        # threads ride the wire server's lifecycle (no-op on NULL obs).
        self.obs.start_ops()
        try:
            if self._loop is not None:
                self._loop.start()
            else:
                self._thread = threading.Thread(
                    target=self._httpd.serve_forever,
                    kwargs={"poll_interval": 0.05},
                    name="arena-wire-server",
                    daemon=True,
                )
                self._thread.start()
        except BaseException:
            # A failed spawn must not strand the rotation/sampling
            # threads start_ops just launched: nobody holds a handle to
            # call close() on a server that never started.
            self._thread = None
            self.obs.stop_ops()
            raise
        return self

    def _started(self):
        if self._loop is not None:
            return self._loop._thread is not None
        return self._thread is not None

    def close(self):
        if self.cache is not None:
            self.server.remove_refresh_listener(self._prerender)
            self.cache.close()
        if self._loop is not None:
            self._loop.close()
        if self._httpd is not None:
            if self._thread is not None:
                self._httpd.shutdown()
                self._thread.join(timeout=10.0)
                self._thread = None
            self._httpd.server_close()
        self.obs.stop_ops()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
