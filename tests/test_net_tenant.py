"""Tenant keys over the wire: `?tenant=` reads, tenant/category submit,
byte cache keyed per tenant, `/log` tenant columns (arena/net/*).

One real `ThreadingHTTPServer` over a `MultiTenantEngine`, same stack
as test_net_wire.py. The named mutation-audit kill here is
`test_wire_unknown_tenant_rejected`: `_validate_tenant` is the wire
sanitizer that keeps an out-of-range tenant id from silently folding
its matches into a neighboring tenant's leaderboard — skip the range
check and the 400s below become 202s.
"""

import numpy as np
import pytest

from arena.net import ArenaHTTPServer, FrontDoor, WireClient
from arena.obs import Observability
from arena.serving import ArenaServer
from arena.tenancy import CategoryRegistry, MultiTenantEngine

P = 32
TENANTS = 3


@pytest.fixture(scope="module")
def wire():
    obs = Observability()
    eng = MultiTenantEngine(
        P, num_tenants=TENANTS, min_bucket=64, obs=obs
    )
    srv = ArenaServer(engine=eng, max_staleness_matches=0, obs=obs)
    frontdoor = FrontDoor(eng, capacity=32, record_applied=True)
    categories = CategoryRegistry(eng, categories=("chat", "code", "vision"))
    server = ArenaHTTPServer(
        srv, frontdoor=frontdoor, categories=categories
    ).start()
    client = WireClient(server.host, server.port)
    yield server, client
    client.close()
    server.close()
    frontdoor.close()
    srv.close()


def _settle(server):
    server.frontdoor.flush()
    server.server.refresh_view()


def test_wire_unknown_tenant_rejected(wire):
    """The named kill for wire-tenant-validation-skipped: every wire
    entry point — submit, the read endpoints, batched /query — rejects
    a tenant id outside [0, num_tenants) with a 400 naming the range,
    and rejects non-integer tenants outright."""
    server, client = wire
    applied_before = server.server.engine.matches_applied
    # Submit: in-bucket-but-inactive (5) and out-of-bucket (99) both 400.
    for bad in (5, 99, -1):
        status, resp = client.submit([1], [2], tenant=bad)
        assert status == 400, (bad, resp)
        assert "unknown tenant" in resp["error"]
    # Reads: same reject, same sanitizer.
    for path in (
        "/leaderboard?limit=3&tenant=5",
        "/player/1?tenant=99",
        "/h2h?a=1&b=2&tenant=-1",
    ):
        status, resp = client.get(path)
        assert status == 400, (path, resp)
        assert "unknown tenant" in resp["error"]
    status, resp = client.get("/leaderboard?limit=3&tenant=x")
    assert status == 400
    status, resp = client.batch_query([{"players": [1], "tenant": 5}])
    assert status == 400 and "unknown tenant" in resp["error"]
    server.frontdoor.flush()
    assert server.server.engine.matches_applied == applied_before


def test_submit_by_tenant_and_category_scope_ratings(wire):
    server, client = wire
    eng = server.server.engine
    status, resp = client.submit([3, 4], [5, 6], tenant=1)
    assert status == 202 and resp["tenant"] == 1
    status, resp = client.submit([7], [8], category="vision")
    assert status == 202 and resp["tenant"] == 2
    status, resp = client.submit(
        [1], [2], tenant=0, category="chat"
    )
    assert status == 400  # one or the other, never both
    status, resp = client.submit([1], [2], category="nope")
    assert status == 400 and "unknown category" in resp["error"]
    _settle(server)
    ratings = np.asarray(eng.ratings)
    assert ratings[1][3] > 1500.0 and ratings[1][5] < 1500.0
    assert ratings[2][7] > 1500.0
    # Tenant-local ids never leak across slots.
    assert ratings[0][3] == 1500.0


def test_tenant_reads_slice_one_view(wire):
    server, client = wire
    _settle(server)
    _status, lb1 = client.get("/leaderboard?limit=5&tenant=1")
    assert lb1["tenant"] == 1
    assert lb1["leaderboard"][0]["player"] in (3, 4)
    assert all(0 <= r["player"] < P for r in lb1["leaderboard"])
    _status, player = client.get("/player/7?tenant=2")
    assert player["tenant"] == 2
    assert player["players"][0]["player"] == 7
    assert player["players"][0]["rating"] > 1500.0
    _status, h2h = client.get("/h2h?a=7&b=8&tenant=2")
    assert h2h["pairs"][0]["p_a_beats_b"] > 0.5
    # No tenant param -> composite admin view, no tenant key.
    _status, admin = client.get("/leaderboard?limit=3")
    assert "tenant" not in admin
    # Batched specs mix tenants against ONE view.
    _status, out = client.batch_query([
        {"players": [3], "tenant": 1},
        {"players": [3], "tenant": 0},
        {"leaderboard": [0, 2]},
    ])
    rs = out["results"]
    assert rs[0]["tenant"] == 1 and rs[0]["players"][0]["rating"] > 1500.0
    assert rs[1]["tenant"] == 0 and rs[1]["players"][0]["rating"] == 1500.0
    assert "tenant" not in rs[2]
    assert rs[0]["view_seq"] == rs[1]["view_seq"] == rs[2]["view_seq"]


def test_byte_cache_keys_on_tenant(wire):
    """The watermark-keyed byte cache must key on tenant: two tenants'
    identical-shaped leaderboard reads are DIFFERENT cache entries, and
    a repeat read hits without cross-tenant bleed."""
    server, client = wire
    srv = server.server
    _settle(server)
    hits_before = srv.obs.registry.counter_sum("arena_wire_cache_hits_total")
    _status, first1 = client.get("/leaderboard?offset=0&limit=4&tenant=1")
    _status, first0 = client.get("/leaderboard?offset=0&limit=4&tenant=0")
    _status, again1 = client.get("/leaderboard?offset=0&limit=4&tenant=1")
    _status, again0 = client.get("/leaderboard?offset=0&limit=4&tenant=0")
    hits_after = srv.obs.registry.counter_sum("arena_wire_cache_hits_total")
    assert hits_after >= hits_before + 2

    def rows(resp):
        return [(r["player"], r["rating"]) for r in resp["leaderboard"]]

    assert rows(again1) == rows(first1)
    assert rows(again0) == rows(first0)
    assert rows(first1) != rows(first0)  # tenant 1 has winners, 0 is idle


def test_log_records_carry_tenant_column(wire):
    server, client = wire
    server.frontdoor.flush()
    _status, log = client.get("/log?after_seq=-1")
    assert log["records"], "submits above must be in the log"
    for rec in log["records"]:
        assert "tenant" in rec
    tenants = {rec["tenant"] for rec in log["records"]}
    assert {1, 2} <= tenants  # the tenant= and category= submits above
    # Replay stays composite: record ids are composite-space ints.
    rec = next(r for r in log["records"] if r["tenant"] == 1)
    assert all(P <= i < 2 * P for i in rec["winners"] + rec["losers"])


def test_as_of_and_tenant_do_not_combine(wire):
    _server, client = wire
    status, resp = client.get("/leaderboard?limit=3&tenant=1&as_of=0")
    assert status == 400
    assert "cannot be combined" in resp["error"]


def test_category_submit_requires_registry():
    obs = Observability()
    eng = MultiTenantEngine(P, num_tenants=1, min_bucket=64, obs=obs)
    srv = ArenaServer(engine=eng, obs=obs)
    frontdoor = FrontDoor(eng, record_applied=True)
    server = ArenaHTTPServer(srv, frontdoor=frontdoor).start()
    client = WireClient(server.host, server.port)
    try:
        status, resp = client.submit([1], [2], category="chat")
        assert status == 400
        assert "no category registry" in resp["error"]
    finally:
        client.close()
        server.close()
        frontdoor.close()
        srv.close()
