"""Serving-layer contracts (arena/serving.py).

The load-bearing property is CRASH-RESTART EQUIVALENCE: ingest K
batches, snapshot at a random boundary, throw the engine away, restore,
replay the remainder — the ratings must be BIT-EXACT equal to the
uninterrupted stream, and the restored grouping must cover every entry
(the delta tail survives the round-trip; restore never re-sorts).
Around it, the contracts a serving surface needs pinned:

- the snapshot REJECT posture: wrong magic/version, truncated or
  corrupt bytes, inconsistent counts → a distinct `SnapshotError`
  naming expected vs found, with the live engine untouched (never a
  silent partial restore);
- a snapshot taken with a non-empty pipeline queue spills the raw
  batches and a restore resubmits them in order (resume mid-stream);
- staleness-bounded reads: the view watermark advances past the
  `max_staleness_matches` bound (the mutation audit carries a mutant
  that freezes it) and reads during an in-progress restore serve the
  last complete view with `stale=True`;
- the batched query API answers every part of one call from ONE view;
- bootstrap (rating, lo, hi) intervals are deterministic under a fixed
  seed;
- production-mode sanitizers count instead of raising (`stats()`).
"""

import json
import threading
import time

import numpy as np
import pytest

from arena import serving
from arena.engine import ArenaEngine
from arena.serving import ArenaServer, SnapshotError

P = 40


def make_matches(n, num_players=P, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.integers(0, num_players, n).astype(np.int32)
    b = ((a + 1 + rng.integers(0, num_players - 1, n)) % num_players).astype(
        np.int32
    )
    return a, b


def random_split(w, l, seed, max_batches=10):
    rng = np.random.default_rng(seed)
    cuts = np.sort(rng.integers(0, len(w) + 1, rng.integers(2, max_batches)))
    bounds = [0, *cuts.tolist(), len(w)]
    return [(w[a:b], l[a:b]) for a, b in zip(bounds, bounds[1:])]


def wait_until(predicate, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() > deadline:
            pytest.fail(f"timed out waiting for {what}")
        time.sleep(0.005)


def assert_grouping_exact(store, num_matches):
    """The restored grouping covers every interleaved entry exactly
    once — the property a dropped delta tail breaks."""
    perm, bounds = store.clone().grouping()
    assert np.array_equal(np.sort(perm), np.arange(2 * num_matches))
    assert int(bounds[-1]) == 2 * num_matches


# --- crash-restart equivalence (the satellite's named property) ------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_crash_restart_replay_is_bit_exact(tmp_path, seed):
    """Ingest K batches, snapshot at a random boundary, DISCARD the
    engine, restore from disk, replay the remainder: ratings bit-exact
    to the uninterrupted stream, grouping complete (the snapshot here
    always carries a NON-EMPTY delta tail — batches are far below the
    compaction floor, so nothing has compacted), and the chunked BT
    refit over the restored store matches the uninterrupted one."""
    w, l = make_matches(1000, seed=seed)
    batches = random_split(w, l, seed=50 + seed)
    cut = int(np.random.default_rng(90 + seed).integers(1, len(batches)))

    uninterrupted = ArenaEngine(P)
    for bw, bl in batches:
        uninterrupted.ingest(bw, bl)

    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    for bw, bl in batches[:cut]:
        srv.engine.ingest(bw, bl)
    assert srv.engine._store.tail_entries > 0  # the tail rides the snapshot
    srv.snapshot(tmp_path / "snap")
    del srv  # the "crash": nothing survives but the on-disk snapshot

    restored = ArenaServer(num_players=P)
    restored.restore(tmp_path / "snap")
    assert restored.engine._store.tail_entries > 0
    for bw, bl in batches[cut:]:
        restored.engine.ingest(bw, bl)

    np.testing.assert_array_equal(
        np.asarray(restored.engine.ratings), np.asarray(uninterrupted.ratings)
    )
    assert restored.engine.matches_ingested == len(w)
    assert_grouping_exact(restored.engine._store, len(w))
    np.testing.assert_allclose(
        np.asarray(restored.engine.refit_incremental(num_iters=20)),
        np.asarray(uninterrupted.refit_incremental(num_iters=20)),
        atol=1e-5,
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_crash_restart_with_nonempty_pipeline_queue(tmp_path, seed):
    """The spill form: snapshot taken while the async pipeline still
    holds raw batches. The queue rides the snapshot (validated batches
    are just int32 arrays), restore resubmits them FIFO, and the
    restored ratings equal the uninterrupted stream bit-exact."""
    w, l = make_matches(600, seed=seed)
    step = 100
    batches = [
        (w[i * step : (i + 1) * step], l[i * step : (i + 1) * step])
        for i in range(6)
    ]
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    eng = srv.engine
    eng.ingest(*batches[0])
    pipe = eng.start_pipeline(capacity=8)
    result = {}

    def snap():
        try:
            result["manifest"] = srv.snapshot(tmp_path / "snap", spill=True)
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            result["error"] = exc

    with eng._store._lock:  # stall the packer inside its first merge
        for bw, bl in batches[1:]:
            eng.ingest_async(bw, bl)
        wait_until(lambda: pipe._packing, what="packer to pick up a batch")
        worker = threading.Thread(target=snap, daemon=True)
        worker.start()
        wait_until(lambda: not pipe._raw, what="queue spill")
    worker.join(timeout=30.0)
    assert "error" not in result, result.get("error")
    manifest = result["manifest"]
    # Batch 1 was mid-pack (always merged + dispatched, never spilled);
    # batches 2..5 were still raw and rode the snapshot.
    assert manifest["queue_batches"] == 4
    assert manifest["queue_matches"] == 4 * step
    assert manifest["num_matches"] == 2 * step

    restored = ArenaServer(num_players=P)
    restored.restore(tmp_path / "snap")
    uninterrupted = ArenaEngine(P)
    for bw, bl in batches:
        uninterrupted.ingest(bw, bl)
    np.testing.assert_array_equal(
        np.asarray(restored.engine.ratings), np.asarray(uninterrupted.ratings)
    )
    assert restored.engine.matches_ingested == len(w)
    assert_grouping_exact(restored.engine._store, len(w))


def test_snapshot_after_compaction_restores_runs_without_resort(tmp_path):
    """Main runs AND a fresh tail both survive: force a compaction
    mid-stream, keep ingesting, snapshot, restore — run/tail split
    preserved exactly (restore installs the arrays, it never
    re-sorts or re-compacts)."""
    w, l = make_matches(800, seed=9)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w[:500], l[:500])
    srv.engine._store.compact()
    srv.engine.ingest(w[500:], l[500:])
    store = srv.engine._store
    assert store._keys.size == 1000 and store.tail_entries == 600
    compactions = store.compactions
    srv.snapshot(tmp_path / "snap")

    restored = ArenaServer(num_players=P)
    restored.restore(tmp_path / "snap")
    rstore = restored.engine._store
    assert rstore._keys.size == 1000 and rstore.tail_entries == 600
    assert rstore.compactions == compactions
    np.testing.assert_array_equal(rstore._keys, store._keys)
    np.testing.assert_array_equal(rstore._pos, store._pos)
    assert_grouping_exact(rstore, 800)


# --- the snapshot reject posture -------------------------------------------


def build_server_with_snapshot(tmp_path, n=300, seed=4):
    w, l = make_matches(n, seed=seed)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w, l)
    srv.snapshot(tmp_path / "snap")
    return srv, tmp_path / "snap"


def assert_reject_leaves_engine_untouched(srv, snap, match):
    before = np.asarray(srv.engine.ratings).copy()
    matches_before = srv.engine.matches_ingested
    store_before = srv.engine._store
    with pytest.raises(SnapshotError, match=match):
        srv.restore(snap)
    assert srv.engine.matches_ingested == matches_before
    assert srv.engine._store is store_before
    np.testing.assert_array_equal(np.asarray(srv.engine.ratings), before)
    assert srv._restoring is False  # the marker is cleared on reject


def test_restore_rejects_mismatched_manifest_version(tmp_path):
    """The version gate names expected vs found and the live engine is
    untouched — the mutation audit carries the check-skipped mutant;
    this is its named kill."""
    srv, snap = build_server_with_snapshot(tmp_path)
    man = snap / serving.MANIFEST_NAME
    doc = json.loads(man.read_text())
    doc["version"] = 99
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(
        srv, snap, match=r"expected 3, found 99"
    )


def test_restore_rejects_corrupt_binary_header(tmp_path):
    srv, snap = build_server_with_snapshot(tmp_path)
    blob = bytearray((snap / serving.ARRAYS_NAME).read_bytes())
    blob[8:12] = (7).to_bytes(4, "little")  # header version field
    (snap / serving.ARRAYS_NAME).write_bytes(bytes(blob))
    assert_reject_leaves_engine_untouched(
        srv, snap, match=r"header version: expected 3, found 7"
    )
    # A payload byte flip past the header is caught by the checksum.
    blob = bytearray((snap / serving.ARRAYS_NAME).read_bytes())
    blob[8:12] = int(serving.SNAPSHOT_VERSION).to_bytes(4, "little")
    blob[-1] ^= 0xFF
    (snap / serving.ARRAYS_NAME).write_bytes(bytes(blob))
    assert_reject_leaves_engine_untouched(srv, snap, match=r"checksum mismatch")


def test_restore_rejects_truncated_arrays(tmp_path):
    srv, snap = build_server_with_snapshot(tmp_path)
    blob = (snap / serving.ARRAYS_NAME).read_bytes()
    (snap / serving.ARRAYS_NAME).write_bytes(blob[: len(blob) // 2])
    assert_reject_leaves_engine_untouched(srv, snap, match=r"truncated")


def test_restore_rejects_wrong_magic_and_missing_pieces(tmp_path):
    srv, snap = build_server_with_snapshot(tmp_path)
    man = snap / serving.MANIFEST_NAME
    doc = json.loads(man.read_text())
    doc["magic"] = "NOTARENA"
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(srv, snap, match=r"bad snapshot magic")
    man.unlink()
    assert_reject_leaves_engine_untouched(srv, snap, match=r"no snapshot manifest")


def test_restore_rejects_inconsistent_counts(tmp_path):
    """Manifest counts disagreeing with the arrays (num_matches edited
    after the fact) is a distinct reject, not a partial restore."""
    srv, snap = build_server_with_snapshot(tmp_path)
    man = snap / serving.MANIFEST_NAME
    doc = json.loads(man.read_text())
    doc["num_matches"] = doc["num_matches"] + 7
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(srv, snap, match=r"match log holds")


def test_restore_rejects_malformed_manifest_fields(tmp_path):
    """Wrong-TYPED manifest fields are a SnapshotError too — never a
    raw TypeError/KeyError leaking out of the loader."""
    srv, snap = build_server_with_snapshot(tmp_path)
    man = snap / serving.MANIFEST_NAME
    pristine = man.read_text()
    doc = json.loads(pristine)
    doc["num_matches"] = "three-hundred"
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(srv, snap, match=r"non-negative int")
    doc = json.loads(pristine)
    doc["k"] = None
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(srv, snap, match=r"must be numeric")
    doc = json.loads(pristine)
    del doc["arrays"][0]["offset"]
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(srv, snap, match=r"malformed snapshot")


def test_snapshot_binary_format_is_versioned_and_checksummed(tmp_path):
    _srv, snap = build_server_with_snapshot(tmp_path)
    blob = (snap / serving.ARRAYS_NAME).read_bytes()
    assert blob[:8] == serving.SNAPSHOT_MAGIC
    assert int.from_bytes(blob[8:12], "little") == serving.SNAPSHOT_VERSION
    doc = json.loads((snap / serving.MANIFEST_NAME).read_text())
    assert doc["magic"] == "ARENASNP" and doc["version"] == serving.SNAPSHOT_VERSION
    assert doc["bin_bytes"] == len(blob)
    names = {entry["name"] for entry in doc["arrays"]}
    assert {"keys", "pos", "tail_keys", "winners", "losers", "ratings"} <= names
    # int32 arrays written raw: the winners entry slices back to the log.
    entry = next(e for e in doc["arrays"] if e["name"] == "winners")
    assert entry["dtype"] == "int32"
    winners = np.frombuffer(
        blob, np.int32, count=entry["length"], offset=entry["offset"]
    )
    assert winners.size == doc["num_matches"]


def test_adopt_state_refuses_nonfresh_engine():
    w, l = make_matches(50, seed=11)
    eng = ArenaEngine(P)
    eng.ingest(w, l)
    donor = ArenaEngine(P)
    with pytest.raises(RuntimeError, match="fresh engine"):
        eng.adopt_state(np.zeros(P, np.float32), donor._store)


# --- staleness-bounded reads -----------------------------------------------


def test_view_watermark_advances_past_staleness_bound():
    """The staleness policy refreshes the view once the ingested
    stream moves more than max_staleness_matches past its watermark —
    the mutation audit carries a never-refreshed mutant; this is its
    named kill."""
    w, l = make_matches(400, seed=12)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w[:100], l[:100])
    first = srv.query(leaderboard=(0, 3))
    assert first["watermark"] == 100 and first["stale"] is False
    srv.engine.ingest(w[100:], l[100:])
    second = srv.query(leaderboard=(0, 3))
    assert second["watermark"] == 400, "stale view served past the bound"
    assert second["staleness"] == 0 and second["stale"] is False
    assert second["view_seq"] > first["view_seq"]


def test_wide_staleness_bound_keeps_serving_the_old_view():
    w, l = make_matches(300, seed=13)
    srv = ArenaServer(num_players=P, max_staleness_matches=1000)
    srv.engine.ingest(w[:200], l[:200])
    first = srv.query(players=[0])
    srv.engine.ingest(w[200:], l[200:])
    second = srv.query(players=[0])
    # Within the bound: same view, honestly reported staleness.
    assert second["view_seq"] == first["view_seq"]
    assert second["watermark"] == first["watermark"]
    assert second["staleness"] == 100 and second["stale"] is False


def test_reads_during_restore_serve_last_view_with_stale_marker(
    tmp_path, monkeypatch
):
    srv, snap = build_server_with_snapshot(tmp_path)
    warm = srv.query(leaderboard=(0, 3))
    in_read = threading.Event()
    release = threading.Event()
    real_read = serving.read_snapshot

    def slow_read(path):
        in_read.set()
        assert release.wait(timeout=30.0)
        return real_read(path)

    monkeypatch.setattr(serving, "read_snapshot", slow_read)
    worker = threading.Thread(target=lambda: srv.restore(snap), daemon=True)
    worker.start()
    wait_until(in_read.is_set, what="restore to reach the snapshot read")
    during = srv.query(leaderboard=(0, 3))
    assert during["stale"] is True
    assert during["view_seq"] == warm["view_seq"]  # the last COMPLETE view
    release.set()
    worker.join(timeout=30.0)
    after = srv.query(leaderboard=(0, 3))
    assert after["stale"] is False
    assert after["view_seq"] > warm["view_seq"]
    assert srv.stats()["stale_serves"] >= 1


# --- the batched query API -------------------------------------------------


def test_query_batched_parts_come_from_one_view():
    w, l = make_matches(500, seed=14)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w, l)
    resp = srv.query(leaderboard=(0, 10), players=[0, 5, 7], pairs=[(0, 1), (1, 0)])
    assert resp["watermark"] == 500
    board = resp["leaderboard"]
    assert [row["rank"] for row in board] == list(range(1, 11))
    ratings = [row["rating"] for row in board]
    assert ratings == sorted(ratings, reverse=True)
    by_id = {row["player"]: row for row in resp["players"]}
    assert set(by_id) == {0, 5, 7}
    r = np.asarray(srv.engine.ratings)
    for p, row in by_id.items():
        assert row["rating"] == pytest.approx(float(r[p]))
        assert row["wins"] == int((w == p).sum())
        assert row["losses"] == int((l == p).sum())
    pab, pba = resp["pairs"]
    assert 0.0 < pab["p_a_beats_b"] < 1.0
    assert pab["p_a_beats_b"] + pba["p_a_beats_b"] == pytest.approx(1.0)


def test_query_pagination_and_validation():
    w, l = make_matches(100, seed=15)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w, l)
    full = srv.query(leaderboard=(0, P))["leaderboard"]
    page = srv.query(leaderboard=(5, 5))["leaderboard"]
    assert [r["player"] for r in page] == [r["player"] for r in full[5:10]]
    past_end = srv.query(leaderboard=(P + 3, 5))["leaderboard"]
    assert past_end == []
    with pytest.raises(ValueError, match="player ids"):
        srv.query(players=[P])
    with pytest.raises(ValueError, match="pair"):
        srv.query(pairs=[(0, P)])
    with pytest.raises(ValueError, match="non-negative"):
        srv.query(leaderboard=(-1, 5))


def test_query_under_concurrent_ingest_is_never_torn():
    """Tier-1 version of the serve bench's torn-view check: a query
    thread hammers the server while the main thread ingests. Every
    response must be internally consistent — ratings from ONE rating
    vector (Elo is zero-sum, so the view's total rating mass is
    conserved), watermark monotone, pages sorted."""
    w, l = make_matches(4000, seed=16)
    srv = ArenaServer(num_players=P, max_staleness_matches=100)
    srv.engine.ingest(w[:500], l[:500])
    stop = threading.Event()
    failures = []
    seen = {"last_watermark": 0, "queries": 0}
    base_mass = P * 1500.0

    def reader():
        while not stop.is_set():
            resp = srv.query(leaderboard=(0, P))
            seen["queries"] += 1
            board = resp["leaderboard"]
            ratings = [row["rating"] for row in board]
            if ratings != sorted(ratings, reverse=True):
                failures.append("unsorted page")
            if abs(sum(ratings) - base_mass) > 1.0:
                failures.append(f"zero-sum broken: {sum(ratings)}")
            if resp["watermark"] < seen["last_watermark"]:
                failures.append("watermark went backwards")
            seen["last_watermark"] = resp["watermark"]

    worker = threading.Thread(target=reader, daemon=True)
    worker.start()
    for start in range(500, 4000, 250):
        srv.engine.ingest(w[start : start + 250], l[start : start + 250])
    stop.set()
    worker.join(timeout=30.0)
    assert not failures, failures[:5]
    assert seen["queries"] > 0
    final = srv.query(leaderboard=(0, 1))
    assert final["watermark"] == 4000


# --- bootstrap confidence intervals ----------------------------------------


def test_query_returns_rating_lo_hi_deterministic_under_seed():
    w, l = make_matches(600, seed=17)

    def build():
        srv = ArenaServer(
            num_players=P, max_staleness_matches=0,
            bootstrap_rounds=8, bootstrap_seed=123,
        )
        srv.engine.ingest(w, l)
        srv.refresh_intervals(batch_size=256)
        return srv

    a, b = build(), build()
    ra = a.query(players=list(range(P)))["players"]
    rb = b.query(players=list(range(P)))["players"]
    for row_a, row_b in zip(ra, rb):
        assert row_a["lo"] == row_b["lo"] and row_a["hi"] == row_b["hi"]
        assert row_a["lo"] <= row_a["hi"]
    # Intervals are real spread, not degenerate points.
    assert any(row["hi"] - row["lo"] > 1.0 for row in ra)
    # A different seed moves the resample.
    c = ArenaServer(
        num_players=P, max_staleness_matches=0,
        bootstrap_rounds=8, bootstrap_seed=7,
    )
    c.engine.ingest(w, l)
    c.refresh_intervals(batch_size=256)
    rc = c.query(players=list(range(P)))["players"]
    assert any(
        row_c["lo"] != row_a["lo"] for row_c, row_a in zip(rc, ra)
    )


def test_intervals_absent_until_refreshed():
    w, l = make_matches(100, seed=18)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w, l)
    row = srv.query(players=[0])["players"][0]
    assert row["lo"] is None and row["hi"] is None


# --- production-mode sanitizers via stats() --------------------------------


def test_stats_counters_and_count_mode_sanitizers():
    """The serving path runs the sanitizers in metrics mode by
    default: warmup compiles land in recompile_events (never a raise),
    the donation guard samples the donating update, and the serving
    counters move."""
    w, l = make_matches(300, seed=19)
    srv = ArenaServer(
        num_players=P, max_staleness_matches=0, donation_sample_every=1
    )
    for start in range(0, 300, 50):
        srv.engine.ingest(w[start : start + 50], l[start : start + 50])
    srv.query(leaderboard=(0, 5))
    stats = srv.stats()
    assert stats["queries"] == 1
    assert stats["view_refreshes"] >= 1
    assert stats["matches_ingested"] == stats["matches_applied"] == 300
    # The engine's one warmup compile was COUNTED, not raised.
    assert stats["recompile_events"] >= 1
    assert stats["donation_calls"] == 6
    assert stats["donation_sampled"] == 6
    # CPU honors donate_argnums, so no skip events on this backend.
    assert stats["donation_skipped"] == 0
    before = stats["recompile_events"]
    srv.engine.ingest(w[:50], l[:50])  # same bucket: no new compile
    assert srv.stats()["recompile_events"] == before


def test_server_constructor_validation():
    with pytest.raises(ValueError, match="exactly one"):
        ArenaServer()
    with pytest.raises(ValueError, match="exactly one"):
        ArenaServer(num_players=P, engine=ArenaEngine(P))
    with pytest.raises(ValueError, match="max_staleness_matches"):
        ArenaServer(num_players=P, max_staleness_matches=-1)


def test_restore_server_cold_start(tmp_path):
    srv, snap = build_server_with_snapshot(tmp_path)
    cold = serving.restore_server(snap, max_staleness_matches=0)
    np.testing.assert_array_equal(
        np.asarray(cold.engine.ratings), np.asarray(srv.engine.ratings)
    )
    assert cold.query(leaderboard=(0, 3))["watermark"] == 300


# --- incremental snapshot chains (PR 18) -----------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_incremental_chain_crash_restart_is_bit_exact(tmp_path, seed):
    """The crash-restart property over a CHAIN: full base + two
    increments cut at random boundaries, crash, restore the chain
    head, replay the remainder — ratings bit-exact vs the
    uninterrupted stream, grouping complete. Nothing compacts here
    (batches stay far below the floor), so both increments reuse the
    base's runs and ship zero keys/pos bytes."""
    w, l = make_matches(1200, seed=seed)
    batches = random_split(w, l, seed=60 + seed, max_batches=12)
    rng = np.random.default_rng(91 + seed)
    cuts = sorted(
        rng.choice(np.arange(1, len(batches) + 1), size=3, replace=True)
    )
    cut1, cut2, cut3 = int(cuts[0]), int(cuts[1]), int(cuts[2])

    uninterrupted = ArenaEngine(P)
    for bw, bl in batches:
        uninterrupted.ingest(bw, bl)

    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    for bw, bl in batches[:cut1]:
        srv.engine.ingest(bw, bl)
    srv.snapshot(tmp_path / "base")
    for bw, bl in batches[cut1:cut2]:
        srv.engine.ingest(bw, bl)
    srv.snapshot(tmp_path / "inc1", base=tmp_path / "base")
    for bw, bl in batches[cut2:cut3]:
        srv.engine.ingest(bw, bl)
    srv.snapshot(tmp_path / "inc2", base=tmp_path / "inc1")
    del srv  # the "crash": only the chain survives

    doc = json.loads((tmp_path / "inc2" / serving.MANIFEST_NAME).read_text())
    assert doc["kind"] == "incremental"
    assert doc["chain_depth"] == 2
    assert doc["base_snapshot"] == "../inc1"
    assert doc["reuses_base_runs"] is True
    keys_entry = next(e for e in doc["arrays"] if e["name"] == "keys")
    assert keys_entry["length"] == 0  # runs ride the base, not the increment

    restored = ArenaServer(num_players=P)
    restored.restore(tmp_path / "inc2")
    for bw, bl in batches[cut3:]:
        restored.engine.ingest(bw, bl)
    np.testing.assert_array_equal(
        np.asarray(restored.engine.ratings), np.asarray(uninterrupted.ratings)
    )
    assert restored.engine.matches_ingested == len(w)
    assert_grouping_exact(restored.engine._store, len(w))


def test_incremental_snapshot_after_compaction_ships_runs(tmp_path):
    """A compaction between base and increment means the base's runs
    are stale: the increment ships its own keys/pos
    (`reuses_base_runs` False) and the restored store's run/tail split
    matches the live one exactly."""
    w, l = make_matches(900, seed=11)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w[:300], l[:300])
    srv.snapshot(tmp_path / "base")
    srv.engine.ingest(w[300:600], l[300:600])
    srv.engine._store.compact()
    srv.engine.ingest(w[600:], l[600:])
    srv.snapshot(tmp_path / "inc", base=tmp_path / "base")

    doc = json.loads((tmp_path / "inc" / serving.MANIFEST_NAME).read_text())
    assert doc["reuses_base_runs"] is False
    assert doc["delta_matches"] == 600
    store = srv.engine._store

    restored = ArenaServer(num_players=P)
    restored.restore(tmp_path / "inc")
    rstore = restored.engine._store
    assert rstore.compactions == store.compactions
    np.testing.assert_array_equal(rstore._keys, store._keys)
    np.testing.assert_array_equal(rstore._pos, store._pos)
    np.testing.assert_array_equal(
        np.asarray(restored.engine.ratings), np.asarray(srv.engine.ratings)
    )
    assert_grouping_exact(rstore, 900)


def build_incremental_chain(tmp_path, n=600, seed=21):
    w, l = make_matches(n, seed=seed)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w[: n // 2], l[: n // 2])
    srv.snapshot(tmp_path / "base")
    srv.engine.ingest(w[n // 2:], l[n // 2:])
    srv.snapshot(tmp_path / "inc", base=tmp_path / "base")
    return srv, tmp_path / "base", tmp_path / "inc"


def test_restore_rejects_truncated_or_corrupt_increment(tmp_path):
    """A torn or tampered INCREMENT is rejected before any state is
    touched — truncation, a payload byte flip, and a delta count that
    disagrees with the shipped arrays each name what broke."""
    srv, _base, inc = build_incremental_chain(tmp_path)
    pristine = (inc / serving.ARRAYS_NAME).read_bytes()
    (inc / serving.ARRAYS_NAME).write_bytes(pristine[: len(pristine) // 2])
    assert_reject_leaves_engine_untouched(srv, inc, match=r"truncated")
    blob = bytearray(pristine)
    blob[-1] ^= 0xFF
    (inc / serving.ARRAYS_NAME).write_bytes(bytes(blob))
    assert_reject_leaves_engine_untouched(srv, inc, match=r"checksum mismatch")
    (inc / serving.ARRAYS_NAME).write_bytes(pristine)
    man = inc / serving.MANIFEST_NAME
    pristine_man = man.read_text()
    doc = json.loads(pristine_man)
    doc["delta_matches"] += 5
    doc["num_matches"] += 5
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(
        srv, inc, match=r"incremental match-log delta"
    )
    # ...and an increment that smuggles full rows is rejected too.
    doc = json.loads(pristine_man)
    doc["kind"] = "full"
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(srv, inc, match=r"must not name a base")


def test_restore_rejects_swapped_or_tampered_base_chain(tmp_path):
    """Chain integrity is pinned by CONTENT, not by path: swapping a
    SELF-CONSISTENT but different base under an increment (same
    players, same match count, different matches — every per-directory
    check passes) is caught by the base-checksum link, tampered
    chain_depth by the depth link, and a self-referencing base by the
    cycle guard. The mutant that skips `_validate_chain_link`'s
    checksum check dies here."""
    srv, base, inc = build_incremental_chain(tmp_path)
    # An impostor base: identical shape and counts, different stream.
    other = ArenaServer(num_players=P, max_staleness_matches=0)
    ow, ol = make_matches(300, seed=777)
    other.engine.ingest(ow, ol)
    other.snapshot(tmp_path / "impostor")
    import shutil

    shutil.rmtree(base)
    shutil.copytree(tmp_path / "impostor", base)
    assert_reject_leaves_engine_untouched(
        srv, inc, match=r"snapshot chain broken at .*cut against base arrays"
    )
    other.close()

    # Tampered chain_depth on the head (the manifest is not inside the
    # arrays checksum — the LINK check still catches it).
    srv2, _base2, inc2 = build_incremental_chain(tmp_path / "t2")
    man = inc2 / serving.MANIFEST_NAME
    doc = json.loads(man.read_text())
    doc["chain_depth"] = 5
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(srv2, inc2, match=r"chain_depth 5")

    # A cycle: the increment naming itself as base never loops forever.
    doc["chain_depth"] = 1
    doc["base_snapshot"] = "../inc"
    man.write_text(json.dumps(doc))
    assert_reject_leaves_engine_untouched(srv2, inc2, match=r"chain cycles")


def test_incremental_snapshot_write_side_rejects_foreign_base(tmp_path):
    """The WRITE side refuses to cut an increment against a base from
    a different arena (player count) or a base AHEAD of the live
    stream — the reject happens before any bytes hit disk."""
    w, l = make_matches(300, seed=31)
    srv = ArenaServer(num_players=P, max_staleness_matches=0)
    srv.engine.ingest(w, l)
    srv.snapshot(tmp_path / "base")
    behind = ArenaServer(num_players=P, max_staleness_matches=0)
    behind.engine.ingest(w[:100], l[:100])
    with pytest.raises(SnapshotError, match=r"AHEAD of the live state"):
        behind.snapshot(tmp_path / "bad", base=tmp_path / "base")
    assert not (tmp_path / "bad").exists()
    foreign = ArenaServer(num_players=P + 1, max_staleness_matches=0)
    fw, fl = make_matches(300, num_players=P + 1, seed=32)
    foreign.engine.ingest(fw, fl)
    with pytest.raises(SnapshotError, match=r"base mismatch on 'num_players'"):
        foreign.snapshot(tmp_path / "bad", base=tmp_path / "base")
    assert not (tmp_path / "bad").exists()
    behind.close()
    foreign.close()
