"""Benchmark: naive-loop vs vectorized/jitted arena rating updates.

The repo's first real performance number. Emits the same one-JSON-line
rc-0 contract `bench.py` honors (one line on stdout no matter what;
internal failures degrade to a distinct error metric; only an
unwritable stdout exits 1), so the driver can record it the same way.

What is measured (all on synthetic matches from a seeded
Bradley–Terry ground truth, so the workload is reproducible):

- ``naive_epoch_s`` — one full pass of batched Elo over the match set
  via `arena/baseline.py`'s per-match Python/NumPy loop.
- ``jit_epoch_s`` — the same pass (same batch semantics, same batch
  size) through the fused, scatter-free jitted epoch
  (`arena.ratings.elo_epoch`), min over repeats after a warmup call
  (compile time excluded, steady-state measured).
- ``ingest_s`` — the one-time NumPy cost of bucketing/grouping the
  match set (`arena.engine.pack_epoch`). Reported separately and also
  folded into ``speedup_incl_ingest``: ingest is paid once per
  dataset, the epoch cost is paid on every pass (Elo refits,
  bootstrap rounds) and every Bradley–Terry iteration, so the
  headline ``value`` is the steady-state update speedup.
- Bradley–Terry: per-MM-iteration time, naive loop vs fused scan.
- If more than one device is visible (or ARENA_BENCH_DEVICES forces a
  CPU device count), the shard_map data-parallel epoch is timed too —
  reported as numbers per device count, with no speedup claim: on this
  1-core image extra host devices share one core.

The two paths' final ratings are compared BEFORE any speedup is
reported — and the comparison is a HARD GATE, not an annotation: if
``max_diff`` exceeds the tolerance, no speedup is computed at all, the
one JSON line carries the distinct ``arena_bench_equivalence_failure``
metric, and the process exits rc 2 (a measured divergence verdict —
distinct from rc 0's in-contract internal-error degradation and from
rc 1, which stays reserved for an unwritable stdout). A speedup over
code computing something different would be fiction, so it is now
impossible to emit one.

A second mode rides the same contract: ``ARENA_BENCH_MODE=ingest``
measures the INCREMENTAL ingestion layer (`arena/ingest.py`) instead —
one JSON line with metric ``arena_ingest`` whose ``value`` is how many
times faster merging a delta into the mergeable CSR grouping is than a
cold re-pack of the combined set (`engine.pack_epoch`, the
repack-the-world pattern this PR removes). The same equivalence hard
gate applies to the incremental path: Elo ratings through
`ArenaEngine.ingest` must match a cold pack + fused epoch within
``ARENA_BENCH_TOL`` AND the chunked Bradley–Terry refit must match the
single-bucket fit within ``ARENA_BENCH_BT_TOL`` — any divergence emits
the ``arena_bench_equivalence_failure`` line and exits rc 2, never a
speedup. Steady-state ingest additionally runs under
`RecompileSentinel` (zero new jit compiles after warmup — a raise
degrades to the internal-error line, so a broken bucket contract can
never report a speedup), and the line records the chunked refit's peak
bucket vs the single-pow2-bucket layout's.

A third mode, ``ARENA_BENCH_MODE=pipeline``, measures the OVERLAPPED
ingest path (`arena/pipeline.py`): the same delta stream is pushed
through synchronous `ArenaEngine.ingest()` and through
`ingest_async()`+`flush()` (background packer thread, bounded queue),
after an identical 100k-match base build on each engine. One JSON line
with metric ``arena_pipeline`` whose ``value`` is the overlap speedup
(sync wall-clock / overlapped wall-clock, best of repeats), plus the
pipeline's own host-pack vs device-dispatch time breakdown. The same
HARD equivalence gate applies: the async ratings must match the sync
ratings (bit-exact by construction — same slots, same jitted update,
same order) AND a cold per-batch `update()` replay, within
``ARENA_BENCH_TOL``; divergence emits the
``arena_bench_equivalence_failure`` line and exits rc 2, never a
speedup. A thread-aware `RecompileSentinel` asserts ZERO steady-state
compiles while the packer thread runs. The line records
``host_cores``: on a single-core host the packer and dispatcher share
one CPU, so the overlap cannot beat sync wall-clock there — the number
is reported as measured, not inflated (same honesty stance as the
sharded path's per-device-count numbers).

A fourth mode, ``ARENA_BENCH_MODE=serve``, measures the SERVING layer
(`arena/serving.py`): snapshot/restore round-trip timing on the
streamed-up base — HARD-gated bit-exact (restored ratings, restored
grouping, and a post-restore resumed stream must all match the live
engine; any divergence emits the ``arena_bench_equivalence_failure``
line and exits rc 2) — then query throughput (the headline ``value``,
queries/s) from a reader thread while the main thread keeps ingesting.
Every response is checked for VIEW TEARING: Elo conserves total rating
mass, so a view mixing two rating vectors breaks conservation
(``max_view_mass_dev``, gated by the same tolerance), pages must be
sorted, watermarks monotone. A thread-aware `RecompileSentinel`
asserts zero steady-state compiles across the serve and ingest
threads; the production-mode sanitizer counters ride in the line.

A fifth mode, ``ARENA_BENCH_MODE=soak``, is the long MIXED-workload
harness (ROADMAP item 5): overlapped ingest + a concurrent query
thread + periodic durable snapshots + periodic bootstrap interval
refreshes, all under the LIVE observability layer (`arena/obs/`). One
``arena_soak`` JSON line reports p50/p99 query latency, ingest
throughput, and the queue-depth and staleness distributions — behind
TWO HARD GATES (rc 2): the production-mode ``recompile_events``
counter must stay at ZERO across the whole measured window (update,
bootstrap, packer thread — the compile-free steady-state contract),
and the final ratings must be equivalent to a sync replay of the same
stream (plus the serve-mode torn-view invariants per response). A
third gate class, ``arena_bench_obs_overhead_failure`` (also rc 2),
rides the ``ingest`` and ``pipeline`` modes: each runs its hot path
under the NullRegistry AND the live registry (order-alternated per
repeat) and fails if live regresses more than ``ARENA_BENCH_OBS_TOL``
(3%; a small absolute floor absorbs scheduler jitter at smoke sizes)
— instrumented runs must also produce IDENTICAL groupings/ratings.

A sixth mode, ``ARENA_BENCH_MODE=frontend``, measures the NETWORK
serving tier (`arena/net/`): N simulated producers and M readers drive
a real `ThreadingHTTPServer` over localhost HTTP — producers POST
batches to /submit (each under its own producer label, admitted into
the front door's global sequence order), readers page /leaderboard,
/player/{id}, and /h2h. One ``arena_frontend`` JSON line reports
queries/s (the headline ``value``) and ingest matches/s over the wire.
THE HARD GATES (rc 2): the final ratings must be bit-exact to a sync
single-producer replay of the front door's applied log in sequence
order (the async==sync property under N writers); a thread-aware
`RecompileSentinel` asserts zero steady-state compiles across every
producer/reader/merge thread; every wire response must be well-formed
(status 200/202, sorted pages, conserved rating mass, monotone
watermarks). A separate FORCED-OVERLOAD phase (merge worker held, shed
knobs tightened) then gates the shedding policy itself: the observed
staleness must stay within the configured bound, every shed batch's
trace must END with the explicit ``pipeline.dropped`` marker, and no
dangling orphan spans may exist at quiescence (summary-batch compiles
in this phase are legitimately outside the steady-state window — the
coalesced shapes are new by construction).

A seventh mode, ``ARENA_BENCH_MODE=replica``, measures the REPLICATED
READ FLEET (`arena/net/replica.py`): the writer cuts a FULL snapshot,
churns ~10% more matches through the front door, then cuts an
INCREMENTAL snapshot (chained on the full) and a second full snapshot
at the same watermark — HARD-gated ``full_bytes >= 5x inc_bytes`` (the
delta cut must actually be a delta). Two replicas restore the
incremental chain and tail the writer's ``GET /log`` over real
localhost HTTP; producers then stream more batches into the writer
WHILE readers page the replicas — the catch-up HARD gate requires both
replicas to reach the writer's settled watermark within a bound, the
bit-exactness HARD gate requires replica ratings identical to the
writer's at equal watermark (``max_rating_diff`` 0.0 — same records,
same order, same kernels), a thread-aware `RecompileSentinel` requires
zero steady-state compiles across writer and replica replay threads,
and the scale-out HARD gate requires the fleet's aggregate read
throughput to hold at least ``ARENA_BENCH_REPLICA_SCALEOUT_MIN`` (0.75)
of the single-server figure — on a single-core image the fleet cannot
exceed one server's CPU ceiling, so the gate polices a structural
penalty in the replica read path (a cache bypass, a per-query replay)
rather than demanding parallel speedup; the measured ratio is reported
for multi-core boxes. The headline ``value`` is the fleet's aggregate
wire queries/s.

An eighth mode, ``ARENA_BENCH_MODE=tenant``, measures MULTI-TENANT
FUSION (`arena/tenancy.py`): thousands of independent leaderboards
riding ONE jitted kernel via tenant-composite segment ids. The engine
starts just past the tenant-bucket midpoint, warms the fused update,
then GROWS to the full tenant count round by round under a
thread-aware `RecompileSentinel` — the within-bucket growth HARD gate
requires ZERO new compiles while tenants are added (the tenant axis is
pow2-bucketed exactly like the row axis). Timed rounds then drive
every tenant's matches through single fused updates; the same per-
tenant streams replay through N DEDICATED single-tenant engines (one
`ArenaEngine` per tenant, warmup excluded from timing) — the speedup
HARD gate requires the batched path at least
``ARENA_BENCH_TENANT_MIN_SPEEDUP`` (5x) faster than the dedicated
loop, and the bit-exactness HARD gate requires EVERY tenant's ratings
row identical (`np.array_equal`, not a tolerance) to its dedicated
engine — including a deliberately empty tenant (zero matches must
leave base ratings untouched bit-for-bit). The ops-plane HARD gate
requires the per-tenant ingest counters
(``arena_tenant_matches_total{tenant=...}``) on ONE live registry to
reconcile exactly with the matches each tenant submitted — one ops
plane, tenant-labeled, not N. The headline ``value`` is the
batched-vs-dedicated speedup.

A ninth mode, ``ARENA_BENCH_MODE=matchloop``, is the MATCHMAKING
PLANE's acceptance harness (`arena/match/`): a deterministic
closed-loop self-play soak. Three arms (active, random, and an active
replay) each stand up a full server — `ArenaServer` + `FrontDoor` +
`Matchmaker` + `ArenaHTTPServer` — and loop proposed matches back
through real localhost HTTP: ``GET /match`` proposes pairings, a
seeded ground-truth skill vector (a TIERED ladder — four hard tiers
two logits apart with a narrow within-tier spread, the regime where
match allocation actually matters: cross-tier matches are nearly
foregone conclusions, so a policy that keeps spending there converges
slowly) simulates the outcomes, and ``POST /submit`` feeds them back,
with periodic `refresh_intervals()` so the active policy has live CI
widths to chase. Each arm tracks the Spearman rank correlation
between served ratings and ground truth and records how many matches
it took to cross ``ARENA_BENCH_MATCHLOOP_CORR`` SUSTAINED for
``ARENA_BENCH_MATCHLOOP_SUSTAIN`` consecutive checks (a single lucky
check is not convergence under Elo's random-walk noise; the recorded
count is the first check of the sustained streak). Four HARD gates (rc 2 + flight
bundle): the convergence gate requires active sampling to reach the
threshold at least ``ARENA_BENCH_MATCHLOOP_MIN_ADVANTAGE`` (1.1x)
fewer matches than random pairing at equal budget; the
seed-reproducibility gate requires the replay arm bit-equal to the
first active arm (`np.array_equal` ratings AND the same
matches-to-threshold); a `RecompileSentinel` over the update,
bootstrap, and pair-scoring kernels requires zero steady-state
compiles; and the SLO-silence gate requires zero alerts fired
(`match-proposal-latency` included) across every arm. The headline
``value`` is the convergence advantage: random's matches-to-threshold
over active's.

Env knobs (all optional): ARENA_BENCH_MODE (elo | ingest | pipeline |
serve | soak | frontend | replica | tenant | matchloop),
ARENA_BENCH_MATCHES (100000), ARENA_BENCH_PLAYERS (1000),
ARENA_BENCH_BATCH (8192), ARENA_BENCH_REPEATS (5), ARENA_BENCH_SEED
(0), ARENA_BENCH_BT_ITERS (25), ARENA_BENCH_TOL (0.5 rating points —
the equivalence gate), ARENA_BENCH_DELTA (10000, ingest mode; also the
pipeline/soak modes' streamed batch size), ARENA_BENCH_BT_TOL (0.01,
ingest
mode — chunked-vs-single BT gate), ARENA_BENCH_STREAM_BATCHES (8,
pipeline mode — streamed batches per repeat), ARENA_BENCH_QUEUE_CAPACITY
(8, pipeline/soak modes), ARENA_BENCH_BOOTSTRAP_ROUNDS (8, serve/soak
modes), ARENA_BENCH_SOAK_BATCHES (16), ARENA_BENCH_SOAK_REFRESH_EVERY
(4), ARENA_BENCH_SOAK_SNAPSHOT_EVERY (4), ARENA_BENCH_OBS_TOL (0.03),
ARENA_BENCH_OBS_ABS_S (0.005), ARENA_BENCH_PRODUCERS (4, frontend
mode), ARENA_BENCH_READERS (2), ARENA_BENCH_FRONTEND_BATCHES (6 per
producer), ARENA_BENCH_OVERLOAD_BATCHES (8 per producer, the forced-
overload phase), ARENA_BENCH_FRONTDOOR_CAPACITY (4, the overload
phase's reorder-buffer bound in batches), ARENA_BENCH_SHED_STALENESS
(2x ARENA_BENCH_DELTA, the overload phase's summary backlog bound in
matches), ARENA_BENCH_REPLICAS (2, replica mode),
ARENA_BENCH_CATCHUP_BATCHES (4 per producer, replica mode's
concurrent-ingest phase), ARENA_BENCH_CATCHUP_TIMEOUT_S (60, the
catch-up lag bound), ARENA_BENCH_READ_WINDOW_S (0.5, each read-
throughput measurement window), ARENA_BENCH_REPLICA_SCALEOUT_MIN
(0.75, the aggregate-vs-single-server floor),
ARENA_BENCH_INC_RATIO_MIN (5.0, the full-vs-incremental snapshot
bytes floor), ARENA_BENCH_TENANTS (256, tenant mode),
ARENA_BENCH_TENANT_PLAYERS (1000, players per tenant),
ARENA_BENCH_TENANT_ROUND (256, matches per tenant per round),
ARENA_BENCH_TENANT_ROUNDS (4, timed rounds),
ARENA_BENCH_TENANT_MIN_SPEEDUP (5.0, the batched-vs-dedicated floor),
ARENA_BENCH_MATCHLOOP_PLAYERS (64, matchloop mode),
ARENA_BENCH_MATCHLOOP_PROPOSALS (16, pairings per /match request),
ARENA_BENCH_MATCHLOOP_BUDGET (20000, the per-arm match budget cap),
ARENA_BENCH_MATCHLOOP_CORR (0.95, the Spearman rank-correlation
threshold each arm races to), ARENA_BENCH_MATCHLOOP_SUSTAIN (6,
consecutive at-or-above-threshold checks that count as convergence),
ARENA_BENCH_MATCHLOOP_REFRESH_EVERY (8,
iterations between bootstrap-interval refreshes),
ARENA_BENCH_MATCHLOOP_MIN_ADVANTAGE (1.1, the active-vs-random
convergence floor), ARENA_BENCH_MATCHLOOP_SLO_S (0.25, the
match-proposal-latency SLO threshold), ARENA_BENCH_DEVICES (unset — forces a host CPU device count for the
sharded path when the backend is not yet initialized),
ARENA_BENCH_HISTORY (unset — append every emitted JSON line to this
JSON Lines file, the input of the `python -m arena.obs.regress`
perf-regression watchdog), ARENA_DEBUG_DIR (unset — where HARD gate
failures write their flight-recorder debug bundle; a temp dir
otherwise. The rc-2 line carries the bundle path as "debug_bundle"
for the instrumented modes: soak/serve/pipeline/ingest).
"""

import json
import os
import pathlib
import shutil
import sys
import tempfile
import threading
import time

# Must precede any JAX computation (backend init reads XLA_FLAGS; the
# flag is inert after that, which the device-count check below detects).
_FORCED_DEVICES = os.environ.get("ARENA_BENCH_DEVICES")
if _FORCED_DEVICES:
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count={_FORCED_DEVICES}"
        ).strip()

_REPO_DIR = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO_DIR) not in sys.path:
    sys.path.insert(0, str(_REPO_DIR))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import bench  # noqa: E402  (exc_detail — the repo-wide error formatting)
from arena import baseline, engine, ingest, ratings, serving, sharding  # noqa: E402
from arena import tenancy  # noqa: E402
from arena import net  # noqa: E402
from arena import obs as obs_pkg  # noqa: E402
from arena.analysis import sanitize  # noqa: E402
from arena.obs import debug as obs_debug  # noqa: E402

# The live observability handle of the CURRENT bench mode, registered
# by each runner that has one (ingest/pipeline/serve/soak). When a
# HARD gate fires, main() flight-records it — the rc-2 line then ships
# a postmortem bundle path ("debug_bundle") next to the verdict
# instead of leaving the operator with a bare exit code.
_ACTIVE_OBS = None


def _register_active_obs(obs):
    global _ACTIVE_OBS
    _ACTIVE_OBS = obs


def _gate_debug_bundle(mode):
    """Dump the registered live obs to a bundle and return its path
    (None when the mode runs uninstrumented, e.g. elo). Best-effort:
    the one-JSON-line contract outranks the bundle, so a failed dump
    degrades to None, never to a crash that eats the verdict line."""
    if _ACTIVE_OBS is None:
        return None
    try:
        root = os.environ.get("ARENA_DEBUG_DIR") or tempfile.mkdtemp(
            prefix="arena-debug-"
        )
        path = pathlib.Path(root) / f"bundle-{mode}"
        obs_debug.dump_debug_bundle(_ACTIVE_OBS, path, config={
            "mode": mode,
            "argv": sys.argv,
            "env": {
                k: v for k, v in os.environ.items()
                if k.startswith("ARENA_")
            },
        })
        return str(path)
    except Exception:  # noqa: BLE001 — the verdict line must still emit
        return None

# Max |rating diff| tolerated between the naive float64 loop and the
# float32 scatter-free path, in rating points on the 1500 scale
# (measured ~2e-4 at the default size; budget leaves room for bigger
# runs without letting a real divergence through).
EQUIVALENCE_TOL = 0.5

# Exit codes: 0 = measured (or in-contract internal-error line),
# 1 = stdout unwritable (no JSON line possible), 2 = the two paths
# DIVERGED beyond tolerance — a measured verdict, never conflated
# with a crash (same discipline as the gate's rc 3/rc 4 split).
EXIT_EQUIVALENCE_FAILURE = 2


class EquivalenceError(AssertionError):
    """The naive and vectorized paths disagree beyond tolerance."""

    def __init__(self, max_diff, tol):
        super().__init__(
            f"max |rating diff| {max_diff:.6g} exceeds tolerance {tol:g}; "
            "no speedup may be reported over a divergent computation"
        )
        self.max_diff = max_diff
        self.tol = tol


# Live-registry instrumentation budget on the measured hot paths,
# relative to the NullRegistry baseline. The absolute floor keeps
# smoke-size runs (tens of ms, where 3% is scheduler noise) from
# flaking; at the acceptance sizes the relative bound is the binding
# one.
OBS_OVERHEAD_TOL = 0.03
OBS_OVERHEAD_ABS_FLOOR_S = 0.005


class ObsOverheadError(AssertionError):
    """The live metrics registry measurably slowed the hot path."""

    def __init__(self, overhead, tol, null_s, live_s):
        super().__init__(
            f"live-registry instrumentation overhead {overhead:.2%} exceeds "
            f"{tol:.0%} (null {null_s:.6f}s vs live {live_s:.6f}s); the "
            "observability layer must stay off the hot path"
        )
        self.overhead = overhead
        self.tol = tol
        self.null_s = null_s
        self.live_s = live_s


def _gate_obs_overhead(null_s, live_s):
    """HARD gate: live-vs-null regression must stay under the relative
    tolerance (or under the absolute floor — smoke-size noise guard)."""
    tol = float(os.environ.get("ARENA_BENCH_OBS_TOL", OBS_OVERHEAD_TOL))
    floor = float(
        os.environ.get("ARENA_BENCH_OBS_ABS_S", OBS_OVERHEAD_ABS_FLOOR_S)
    )
    overhead = live_s / null_s - 1.0
    if overhead > tol and (live_s - null_s) > floor:
        raise ObsOverheadError(overhead, tol, null_s, live_s)
    return {
        "null_s": round(null_s, 6),
        "live_s": round(live_s, 6),
        "overhead_frac": round(overhead, 4),
        "tolerance": tol,
        "abs_floor_s": floor,
    }


class SoakGateError(AssertionError):
    """A soak-bench hard gate failed (recompiles in the steady state)."""


class FrontendGateError(AssertionError):
    """A frontend-bench hard gate failed: the shedding policy broke its
    staleness bound, a shed trace did not end with its dropped marker,
    dangling orphan spans survived quiescence, or the forced overload
    failed to shed at all (an un-exercised gate is no gate)."""


class ReplicaGateError(AssertionError):
    """A replica-bench hard gate failed: the incremental snapshot gave
    up its size win over a full cut, the replica fleet's aggregate read
    throughput fell structurally below one server's, catch-up lag blew
    its bound under concurrent wire ingest, or a steady-state record
    replay recompiled."""


class TenantGateError(AssertionError):
    """A tenant-bench hard gate failed: the fused multi-tenant update
    fell below the speedup floor over dedicated per-tenant engines, a
    tenant's ratings diverged bitwise from its dedicated reference,
    within-bucket tenant growth recompiled, or the tenant-labeled ops
    plane failed to reconcile with the per-tenant match counts."""


class MatchloopGateError(AssertionError):
    """A matchloop hard gate failed: active sampling did not beat
    random pairing to the ground-truth rank-correlation threshold at
    equal match budget, two identical closed-loop runs diverged (the
    seed-reproducibility contract), a steady-state proposal/update/
    bootstrap shape recompiled, or an SLO objective fired during the
    soak."""


def _env_int(name, default):
    return int(os.environ.get(name, default))


def make_matches(num_matches, num_players, seed):
    """Synthetic outcomes from a Bradley–Terry ground truth: random
    pairings, winner sampled from true win probability."""
    rng = np.random.default_rng(seed)
    a = rng.integers(0, num_players, num_matches)
    b = (a + 1 + rng.integers(0, num_players - 1, num_matches)) % num_players
    strength = np.linspace(2.0, -2.0, num_players)  # log-strengths
    p_a_wins = 1.0 / (1.0 + np.exp(strength[b] - strength[a]))
    a_wins = rng.random(num_matches) < p_a_wins
    winners = np.where(a_wins, a, b).astype(np.int32)
    losers = np.where(a_wins, b, a).astype(np.int32)
    return winners, losers


def _best_of(fn, repeats):
    """Min wall-clock over repeats; fn must block on its result."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_benchmark():
    num_matches = _env_int("ARENA_BENCH_MATCHES", 100_000)
    num_players = _env_int("ARENA_BENCH_PLAYERS", 1_000)
    batch = _env_int("ARENA_BENCH_BATCH", 8_192)
    repeats = _env_int("ARENA_BENCH_REPEATS", 5)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    bt_iters = _env_int("ARENA_BENCH_BT_ITERS", 25)

    winners, losers = make_matches(num_matches, num_players, seed)

    # --- naive baseline: full Elo pass, per-match loop ---------------
    t0 = time.perf_counter()
    naive_ratings = baseline.elo_epoch_naive(num_players, winners, losers, batch)
    naive_epoch_s = time.perf_counter() - t0

    # --- ingest (one-time): bucket + group the match set -------------
    t0 = time.perf_counter()
    packed = engine.pack_epoch(num_players, winners, losers, batch)
    jax.block_until_ready(packed.perms)
    ingest_s = time.perf_counter() - t0

    # --- fused jitted epoch ------------------------------------------
    epoch_fn = ratings.jit_elo_epoch(num_players, donate=False)
    r0 = jnp.full((num_players,), ratings.DEFAULT_BASE, jnp.float32)
    args = (packed.winners, packed.losers, packed.valid, packed.perms, packed.bounds)
    jit_ratings = epoch_fn(r0, *args)  # warmup: compile excluded
    jax.block_until_ready(jit_ratings)
    jit_epoch_s = _best_of(
        lambda: jax.block_until_ready(epoch_fn(r0, *args)), repeats
    )

    max_diff = float(np.abs(np.asarray(jit_ratings) - naive_ratings).max())
    tol = float(os.environ.get("ARENA_BENCH_TOL", EQUIVALENCE_TOL))
    equivalence_ok = max_diff < tol
    if not equivalence_ok:
        # Hard gate: nothing below (speedup, BT, sharded numbers) is
        # computed or reported over a divergent pair of paths.
        raise EquivalenceError(max_diff, tol)
    speedup = naive_epoch_s / jit_epoch_s

    # --- Bradley–Terry: per-MM-iteration, naive vs fused -------------
    win_counts = np.bincount(winners, minlength=num_players).astype(np.float64)
    t0 = time.perf_counter()
    baseline.bt_mm_step_naive(
        np.ones(num_players), winners.tolist(), losers.tolist(), win_counts
    )
    bt_naive_iter_s = time.perf_counter() - t0

    whole = engine.pack_batch(
        num_players, winners, losers, min_bucket=engine.bucket_size(num_matches)
    )
    wc32 = jnp.asarray(win_counts.astype(np.float32))
    bt_args = (whole.winners, whole.losers, whole.valid, whole.perm, whole.bounds)
    bt_fit_fn = ratings.jit_bt_fit(num_players, num_iters=bt_iters)

    def bt_run():
        return bt_fit_fn(*bt_args, wc32)

    jax.block_until_ready(bt_run())  # warmup
    bt_jit_iter_s = _best_of(lambda: jax.block_until_ready(bt_run()), repeats) / bt_iters

    # --- sharded path (only meaningful with >1 device) ---------------
    sharded = None
    ndev = len(jax.devices())
    if ndev > 1:
        mesh = sharding.build_mesh()
        sharded_fn = sharding.jit_sharded_elo_epoch(mesh)
        sharded_args = (packed.winners, packed.losers, packed.valid)

        def sharded_run():
            return jax.block_until_ready(
                sharded_fn(jnp.full((num_players,), ratings.DEFAULT_BASE), *sharded_args)
            )

        sharded_run()  # warmup (also compiles)
        sharded_epoch_s = _best_of(sharded_run, repeats)
        sharded = {
            "devices": ndev,
            "epoch_s": round(sharded_epoch_s, 6),
            "matches_per_s": round(num_matches / sharded_epoch_s),
            "note": "CPU host devices share cores; correctness/path proof, not a scaling claim",
        }

    return {
        "metric": "arena_elo_update_speedup",
        "value": round(speedup, 2),
        "unit": "x_vs_naive_baseline",
        "vs_baseline": None,
        "params": {
            "num_matches": num_matches,
            "num_players": num_players,
            "batch_size": batch,
            "repeats": repeats,
            "seed": seed,
        },
        "elo": {
            "naive_epoch_s": round(naive_epoch_s, 6),
            "jit_epoch_s": round(jit_epoch_s, 6),
            "ingest_s": round(ingest_s, 6),
            "naive_matches_per_s": round(num_matches / naive_epoch_s),
            "jit_matches_per_s": round(num_matches / jit_epoch_s),
            "jit_update_latency_us_per_batch": round(
                jit_epoch_s / packed.winners.shape[0] * 1e6, 1
            ),
            "speedup_incl_ingest": round(naive_epoch_s / (jit_epoch_s + ingest_s), 2),
        },
        "bt": {
            "iters": bt_iters,
            "naive_iter_s": round(bt_naive_iter_s, 6),
            "jit_iter_s": round(bt_jit_iter_s, 6),
            "iter_speedup": round(bt_naive_iter_s / bt_jit_iter_s, 2),
        },
        "equivalence_ok": equivalence_ok,
        "max_rating_diff": round(max_diff, 6),
        "sharded": sharded,
    }


def _batch_slices(total, batch):
    return [(start, min(start + batch, total)) for start in range(0, total, batch)]


def run_ingest_benchmark():
    """The incremental-ingest comparison: merge a delta into a live
    mergeable grouping vs cold re-pack of the combined set, with the
    equivalence gate extended to the incremental Elo and chunked BT
    paths and a RecompileSentinel over steady-state ingest."""
    base_matches = _env_int("ARENA_BENCH_MATCHES", 100_000)
    delta_matches = _env_int("ARENA_BENCH_DELTA", 10_000)
    num_players = _env_int("ARENA_BENCH_PLAYERS", 1_000)
    batch = _env_int("ARENA_BENCH_BATCH", 8_192)
    repeats = _env_int("ARENA_BENCH_REPEATS", 5)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    bt_iters = _env_int("ARENA_BENCH_BT_ITERS", 25)
    chunk_entries = _env_int(
        "ARENA_BENCH_CHUNK_ENTRIES", ingest.DEFAULT_CHUNK_ENTRIES
    )
    total = base_matches + delta_matches

    winners, losers = make_matches(total, num_players, seed)

    # --- cold re-pack of the COMBINED set (what absorbing the delta
    # costs today: the whole-set grouping recomputed from scratch) ----
    cold_pack_s = _best_of(
        lambda: jax.block_until_ready(
            engine.pack_epoch(num_players, winners, losers, batch).perms
        ),
        repeats,
    )

    # --- incremental: merge ONLY the delta into a live base grouping -
    base_csr = ingest.MergeableCSR(num_players)
    for start, stop in _batch_slices(base_matches, batch):
        base_csr.add(winners[start:stop], losers[start:stop])
    base_csr.compact()
    delta_slices = [
        (base_matches + a, base_matches + b)
        for a, b in _batch_slices(delta_matches, batch)
    ]
    incremental_merge_s = float("inf")
    live = None
    for _ in range(repeats):
        live = base_csr.clone()  # clone cost excluded: it models the
        # already-resident base, not work the merge performs
        t0 = time.perf_counter()
        for start, stop in delta_slices:
            live.add(winners[start:stop], losers[start:stop])
        live.compact()
        incremental_merge_s = min(
            incremental_merge_s, time.perf_counter() - t0
        )
    speedup = cold_pack_s / incremental_merge_s

    # --- instrumentation overhead HARD gate: the WHOLE-SET build
    # (every add + every LSM compaction — the full instrumented hot
    # path, a measurement region large enough that 3% is a real
    # budget, not scheduler jitter) with the LIVE registry recording
    # must stay within tolerance of the NullRegistry build, and must
    # produce the IDENTICAL grouping (instrumentation never touches
    # data). Null and live alternate within each repeat so cache and
    # scheduler state favor neither side. ----------------------------
    obs_live = obs_pkg.Observability()
    _register_active_obs(obs_live)
    # The ops plane runs LIVE through the measured region (PR 13): the
    # <3% budget covers window rotation + profiler sampling, not just
    # the registry writes.
    obs_live.enable_ops(interval_s=0.5)
    # One-shot bench process: a gate failure raises out, the rc-2
    # wrapper dumps the debug bundle and the process exits — the
    # daemonized ops threads die with it, so no try/finally here.
    obs_live.start_ops()  # jaxlint: disable=missing-finally-for-paired-call
    all_slices = _batch_slices(total, batch)
    null_build_s = float("inf")
    live_build_s = float("inf")
    built_null = built_live = None

    def _build(csr):
        t0 = time.perf_counter()
        for start, stop in all_slices:
            csr.add(winners[start:stop], losers[start:stop])
        csr.compact()
        return time.perf_counter() - t0

    for r in range(repeats):
        builds = [
            (ingest.MergeableCSR(num_players), False),
            (ingest.MergeableCSR(num_players, obs=obs_live), True),
        ]
        if r % 2:
            builds.reverse()
        for csr, is_live in builds:
            elapsed = _build(csr)
            if is_live:
                live_build_s = min(live_build_s, elapsed)
                built_live = csr
            else:
                null_build_s = min(null_build_s, elapsed)
                built_null = csr
    obs_gate = _gate_obs_overhead(null_build_s, live_build_s)
    tol = float(os.environ.get("ARENA_BENCH_TOL", EQUIVALENCE_TOL))
    perm_null, bounds_null = built_null.grouping()
    perm_live, bounds_live = built_live.grouping()
    if not (
        np.array_equal(perm_null, perm_live)
        and np.array_equal(bounds_null, bounds_live)
    ):
        raise EquivalenceError(float("inf"), tol)
    obs_live.stop_ops()
    obs_gate["spans_recorded"] = obs_live.tracer.recorded
    obs_gate["csr_merges_counted"] = obs_live.registry.counter_sum(
        "arena_ingest_matches_total"
    )
    obs_gate["window_rotations"] = obs_live.windows.health()["rotations"]
    obs_gate["profiler_samples"] = obs_live.profiler.samples

    # --- equivalence gate, Elo: the incremental engine path must land
    # on the same ratings as a cold pack + fused epoch ----------------
    eng = engine.ArenaEngine(num_players)
    chunks = _batch_slices(total, batch)
    eng.ingest(winners[chunks[0][0] : chunks[0][1]], losers[chunks[0][0] : chunks[0][1]])
    sentinel = sanitize.RecompileSentinel(update=eng.num_compiles)
    for start, stop in chunks[1:-1]:
        eng.ingest(winners[start:stop], losers[start:stop])
    # Steady state means ZERO new compiles: an unbucketed shape leaking
    # into the jitted signature raises here (degrading to the
    # internal-error line — no speedup is ever reported over a broken
    # bucket contract).
    sentinel.assert_no_new_compiles()
    if len(chunks) > 1:
        start, stop = chunks[-1]
        eng.ingest(winners[start:stop], losers[start:stop])  # partial
        # bucket: may legitimately compile ONE new entry, outside the
        # steady-state window.
    ratings_incremental = np.asarray(eng.ratings)

    packed = engine.pack_epoch(num_players, winners, losers, batch)
    epoch_fn = ratings.jit_elo_epoch(num_players, donate=False)
    r0 = jnp.full((num_players,), ratings.DEFAULT_BASE, jnp.float32)
    ratings_cold = np.asarray(
        epoch_fn(
            r0, packed.winners, packed.losers, packed.valid, packed.perms,
            packed.bounds,
        )
    )
    max_diff = float(np.abs(ratings_incremental - ratings_cold).max())
    tol = float(os.environ.get("ARENA_BENCH_TOL", EQUIVALENCE_TOL))
    if not max_diff < tol:
        raise EquivalenceError(max_diff, tol)

    # --- equivalence gate + peak bucket, BT: chunked refit vs the
    # single-pow2-bucket fit ------------------------------------------
    single_bucket = engine.bucket_size(total)
    whole = engine.pack_batch(num_players, winners, losers, min_bucket=single_bucket)
    win_counts = jnp.asarray(
        np.bincount(winners, minlength=num_players).astype(np.float32)
    )
    single_fit = ratings.jit_bt_fit(num_players, num_iters=bt_iters)

    def single_run():
        return single_fit(
            whole.winners, whole.losers, whole.valid, whole.perm, whole.bounds,
            win_counts,
        )

    single_strengths = np.asarray(jax.block_until_ready(single_run()))  # warmup
    single_iter_s = _best_of(
        lambda: jax.block_until_ready(single_run()), repeats
    ) / bt_iters

    chunked_strengths = np.asarray(
        eng.refit_incremental(num_iters=bt_iters, chunk_entries=chunk_entries)
    )
    chunked_iter_s = _best_of(
        lambda: jax.block_until_ready(
            eng.refit_incremental(num_iters=bt_iters, chunk_entries=chunk_entries)
        ),
        repeats,
    ) / bt_iters

    max_strength_diff = float(
        np.abs(chunked_strengths - single_strengths).max()
    )
    bt_tol = float(os.environ.get("ARENA_BENCH_BT_TOL", 0.01))
    if not max_strength_diff < bt_tol:
        raise EquivalenceError(max_strength_diff, bt_tol)

    return {
        "metric": "arena_ingest",
        "value": round(speedup, 2),
        "unit": "x_vs_cold_repack",
        "vs_baseline": None,
        "params": {
            "base_matches": base_matches,
            "delta_matches": delta_matches,
            "num_players": num_players,
            "batch_size": batch,
            "repeats": repeats,
            "seed": seed,
            "chunk_entries": chunk_entries,
        },
        "ingest": {
            "cold_pack_s": round(cold_pack_s, 6),
            "incremental_merge_s": round(incremental_merge_s, 6),
            "delta_matches_per_s": round(delta_matches / incremental_merge_s),
            "compactions": live.compactions,
            "staging_slots": eng._staging.slots_allocated,
            "steady_state_new_compiles": 0,  # sentinel raised otherwise
        },
        "obs": obs_gate,
        "bt": {
            "iters": bt_iters,
            "single_iter_s": round(single_iter_s, 6),
            "chunked_iter_s": round(chunked_iter_s, 6),
            # The memory-cliff fact: the chunked path's largest padded
            # buffer (one chunk) vs the single pow2 pad (2*bucket).
            "single_bucket_entries": 2 * single_bucket,
            "chunked_peak_entries": chunk_entries,
            "peak_bucket_ratio": round(2 * single_bucket / chunk_entries, 2),
        },
        "equivalence_ok": True,
        "max_rating_diff": round(max_diff, 6),
        "max_strength_diff": round(max_strength_diff, 6),
    }


def run_pipeline_benchmark():
    """The overlapped-ingest comparison: the SAME stream of batches
    through sync `ingest()` vs `ingest_async()`+`flush()`, identical
    base builds, with the equivalence hard gate over async-vs-sync and
    async-vs-cold-update ratings and a thread-aware RecompileSentinel
    over the whole streamed (steady-state) window."""
    base_matches = _env_int("ARENA_BENCH_MATCHES", 100_000)
    stream_batch = _env_int("ARENA_BENCH_DELTA", 10_000)
    stream_batches = _env_int("ARENA_BENCH_STREAM_BATCHES", 8)
    num_players = _env_int("ARENA_BENCH_PLAYERS", 1_000)
    batch = _env_int("ARENA_BENCH_BATCH", 8_192)
    repeats = _env_int("ARENA_BENCH_REPEATS", 5)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    capacity = _env_int("ARENA_BENCH_QUEUE_CAPACITY", 8)

    total = base_matches + stream_batch * (1 + stream_batches * repeats)
    winners, losers = make_matches(total, num_players, seed)

    # Four engines, identical histories: sync ingest (the comparator),
    # overlapped ingest (the claim), cold per-batch update (the
    # equivalence anchor — fresh pack_batch allocations, no staging),
    # and overlapped ingest under the LIVE metrics registry (the
    # instrumentation-overhead gate's subject; the other three run the
    # default NullRegistry, i.e. the pre-instrumentation behavior).
    obs_live = obs_pkg.Observability()
    _register_active_obs(obs_live)
    # Windows + profiler run live through the measured streams (PR 13):
    # the <3% budget covers the whole ops plane, not just the registry.
    obs_live.enable_ops(interval_s=0.5)
    # One-shot bench process (see run_ingest_benchmark): on a gate
    # failure the process exits and the daemon ops threads die with it.
    obs_live.start_ops()  # jaxlint: disable=missing-finally-for-paired-call
    eng_sync = engine.ArenaEngine(num_players)
    eng_async = engine.ArenaEngine(num_players)
    eng_cold = engine.ArenaEngine(num_players)
    eng_obs = engine.ArenaEngine(num_players, obs=obs_live)
    eng_async.start_pipeline(capacity=capacity)
    eng_obs.start_pipeline(capacity=capacity)
    for start, stop in _batch_slices(base_matches, batch):
        w, l = winners[start:stop], losers[start:stop]
        eng_sync.ingest(w, l)
        eng_async.ingest(w, l)
        eng_cold.update(w, l)
        eng_obs.ingest(w, l)

    # Warmup: the first stream-sized batch touches the stream bucket
    # (one legitimate compile + slot pair per engine) and runs through
    # each engine's OWN path, keeping all three histories identical.
    w0, l0 = (
        winners[base_matches : base_matches + stream_batch],
        losers[base_matches : base_matches + stream_batch],
    )
    eng_sync.ingest(w0, l0)
    eng_cold.update(w0, l0)
    eng_async.ingest_async(w0, l0)
    eng_async.flush()
    eng_obs.ingest_async(w0, l0)
    eng_obs.flush()

    sentinel = sanitize.RecompileSentinel(
        sync=eng_sync.num_compiles, overlapped=eng_async.num_compiles
    )
    sync_s = float("inf")
    async_s = float("inf")
    obs_async_s = float("inf")
    offset = base_matches + stream_batch

    def _stream_async(eng, slices):
        """One overlapped stream, flushed — flush() blocks on the
        ratings, so the wall clock includes the device work."""
        t0 = time.perf_counter()
        for start, stop in slices:
            eng.ingest_async(winners[start:stop], losers[start:stop])
        eng.flush()
        return time.perf_counter() - t0

    for r in range(repeats):
        slices = [
            (offset + i * stream_batch, offset + (i + 1) * stream_batch)
            for i in range(stream_batches)
        ]
        offset += stream_batches * stream_batch
        t0 = time.perf_counter()
        for start, stop in slices:
            eng_sync.ingest(winners[start:stop], losers[start:stop])
        jax.block_until_ready(eng_sync.ratings)
        sync_s = min(sync_s, time.perf_counter() - t0)
        # Null-obs and live-obs streams alternate order per repeat, so
        # the overhead gate compares runs with symmetric cache and
        # scheduler state (both engines consume every slice either way).
        streams = [(eng_async, False), (eng_obs, True)]
        if r % 2:
            streams.reverse()
        for eng_s, is_live in streams:
            elapsed = _stream_async(eng_s, slices)
            if is_live:
                obs_async_s = min(obs_async_s, elapsed)
            else:
                async_s = min(async_s, elapsed)
        for start, stop in slices:
            eng_cold.update(winners[start:stop], losers[start:stop])
    # Zero new compiles across EVERY streamed batch on both paths — in
    # pipeline mode the steady-state window is the entire measured
    # stream, packer thread included.
    sentinel.assert_no_new_compiles()

    r_sync = np.asarray(eng_sync.ratings)
    r_async = np.asarray(eng_async.flush())
    r_cold = np.asarray(eng_cold.ratings)
    r_obs = np.asarray(eng_obs.flush())
    tol = float(os.environ.get("ARENA_BENCH_TOL", EQUIVALENCE_TOL))
    max_async_diff = float(np.abs(r_async - r_sync).max())
    if not max_async_diff < tol:
        raise EquivalenceError(max_async_diff, tol)
    max_cold_diff = float(np.abs(r_async - r_cold).max())
    if not max_cold_diff < tol:
        raise EquivalenceError(max_cold_diff, tol)
    # The instrumented engine consumed the same stream: identical
    # ratings (instrumentation never touches data) AND within the
    # overhead budget (HARD gate, rc 2 on breach).
    if not np.array_equal(r_obs, r_async):
        raise EquivalenceError(float(np.abs(r_obs - r_async).max()), 0.0)
    obs_live.stop_ops()
    obs_gate = _gate_obs_overhead(async_s, obs_async_s)
    obs_gate["spans_recorded"] = obs_live.tracer.recorded
    obs_gate["window_rotations"] = obs_live.windows.health()["rotations"]
    obs_gate["profiler_samples"] = obs_live.profiler.samples
    eng_obs.shutdown()
    speedup = sync_s / async_s

    pipe = eng_async._pipeline
    host_pack_s = pipe.host_pack_s
    dispatch_s = pipe.dispatch_s
    batches_through = pipe.completed
    dropped = pipe.dropped_batches
    eng_async.shutdown()

    host_cores = os.cpu_count() or 1
    note = (
        "single host core: packer and dispatcher share one CPU, so the "
        "overlap cannot beat sync wall-clock here; the pipeline shape "
        "(bounded queue, slot lifetime, drain protocol) is what a real "
        "accelerator host overlaps with device compute"
        if host_cores == 1
        else None
    )
    streamed = stream_batch * stream_batches
    return {
        "metric": "arena_pipeline",
        "value": round(speedup, 2),
        "unit": "x_vs_sync_ingest",
        "vs_baseline": None,
        "params": {
            "base_matches": base_matches,
            "stream_batch": stream_batch,
            "stream_batches": stream_batches,
            "num_players": num_players,
            "batch_size": batch,
            "repeats": repeats,
            "seed": seed,
            "queue_capacity": capacity,
            "policy": pipe.policy,
            "host_cores": host_cores,
        },
        "pipeline": {
            "sync_stream_s": round(sync_s, 6),
            "overlapped_stream_s": round(async_s, 6),
            "stream_matches_per_s": round(streamed / async_s),
            # The breakdown the overlap exists to exploit: host packing
            # (store merge + slot fill, packer thread) vs device
            # dispatch (jitted update issue + apply, dispatching thread),
            # summed over every async batch including warmup.
            "host_pack_s": round(host_pack_s, 6),
            "dispatch_s": round(dispatch_s, 6),
            "pack_ms_per_batch": round(host_pack_s / batches_through * 1e3, 3),
            "dispatch_ms_per_batch": round(dispatch_s / batches_through * 1e3, 3),
            "batches_through_pipeline": batches_through,
            "dropped_batches": dropped,
            "steady_state_new_compiles": 0,  # sentinel raised otherwise
            "note": note,
        },
        "obs": obs_gate,
        "equivalence_ok": True,
        "max_rating_diff": round(max_async_diff, 6),
        "max_rating_diff_vs_cold": round(max_cold_diff, 6),
    }


def run_serve_benchmark():
    """The serving-layer measurement: snapshot/restore round-trip on a
    streamed-up base (HARD equivalence gate — the restored engine must
    match the live one bit-exactly and must continue the stream to the
    same ratings), then query throughput from a second thread while
    the main thread keeps ingesting. Every query response is checked
    for view tearing (Elo is zero-sum, so a view mixing two rating
    vectors breaks conservation; pages must be sorted; watermarks must
    be monotone) and a thread-aware RecompileSentinel asserts zero
    steady-state compiles across BOTH threads."""
    base_matches = _env_int("ARENA_BENCH_MATCHES", 100_000)
    stream_batch = _env_int("ARENA_BENCH_DELTA", 10_000)
    stream_batches = _env_int("ARENA_BENCH_STREAM_BATCHES", 8)
    num_players = _env_int("ARENA_BENCH_PLAYERS", 1_000)
    batch = _env_int("ARENA_BENCH_BATCH", 8_192)
    repeats = _env_int("ARENA_BENCH_REPEATS", 5)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    bootstrap_rounds = _env_int("ARENA_BENCH_BOOTSTRAP_ROUNDS", 8)
    tol = float(os.environ.get("ARENA_BENCH_TOL", EQUIVALENCE_TOL))

    total = base_matches + stream_batch * (1 + stream_batches)
    winners, losers = make_matches(total, num_players, seed)

    srv = serving.ArenaServer(
        num_players=num_players,
        max_staleness_matches=stream_batch,
        bootstrap_rounds=bootstrap_rounds,
    )
    _register_active_obs(srv.obs)
    for start, stop in _batch_slices(base_matches, batch):
        srv.engine.ingest(winners[start:stop], losers[start:stop])

    snap_root = pathlib.Path(tempfile.mkdtemp(prefix="arena-serve-bench-"))
    try:
        snap_dir = snap_root / "snap"
        snapshot_s = _best_of(lambda: srv.snapshot(snap_dir), repeats)
        manifest = json.loads((snap_dir / serving.MANIFEST_NAME).read_text())
        restored = serving.ArenaServer(
            num_players=num_players, max_staleness_matches=stream_batch
        )
        restore_s = _best_of(
            lambda: (
                restored.restore(snap_dir),
                jax.block_until_ready(restored.engine.ratings),
            ),
            repeats,
        )

        # --- HARD gate 1: the round-trip is bit-exact (ratings AND the
        # grouping — a dropped delta tail or re-sorted runs would show
        # here as structural divergence) ------------------------------
        r_live = np.asarray(srv.engine.ratings)
        r_restored = np.asarray(restored.engine.ratings)
        max_diff = float(np.abs(r_restored - r_live).max())
        if not max_diff < tol:
            raise EquivalenceError(max_diff, tol)
        perm_live, bounds_live = srv.engine._store.clone().grouping()
        perm_rest, bounds_rest = restored.engine._store.clone().grouping()
        if not (
            np.array_equal(perm_live, perm_rest)
            and np.array_equal(bounds_live, bounds_rest)
        ):
            raise EquivalenceError(float("inf"), tol)

        # --- HARD gate 2: the restored engine RESUMES the stream to
        # the same ratings (warmup batch doubles as the stream-bucket
        # compile, outside the steady-state window) -------------------
        w0 = winners[base_matches : base_matches + stream_batch]
        l0 = losers[base_matches : base_matches + stream_batch]
        srv.engine.ingest(w0, l0)
        restored.engine.ingest(w0, l0)
        max_resume_diff = float(
            np.abs(
                np.asarray(restored.engine.ratings)
                - np.asarray(srv.engine.ratings)
            ).max()
        )
        if not max_resume_diff < tol:
            raise EquivalenceError(max_resume_diff, tol)

        # --- query throughput under concurrent ingest ----------------
        # Warmup: intervals (their epoch compile), one query (first
        # view), then the sentinel pins the steady state across both
        # threads.
        srv.refresh_intervals(batch_size=batch)
        srv.query(leaderboard=(0, 10), players=[0], pairs=[(0, 1)])
        sentinel = sanitize.RecompileSentinel(update=srv.engine.num_compiles)
        base_mass = num_players * float(ratings.DEFAULT_BASE)
        stop_event = threading.Event()
        torn = []
        counts = {"queries": 0}
        max_mass_dev = [0.0]

        def reader():
            last_watermark = 0
            ids = list(range(0, num_players, max(1, num_players // 8)))
            while not stop_event.is_set():
                resp = srv.query(
                    leaderboard=(0, 10), players=ids, pairs=[(0, 1)]
                )
                counts["queries"] += 1
                page = [row["rating"] for row in resp["leaderboard"]]
                if page != sorted(page, reverse=True):
                    torn.append("unsorted leaderboard page")
                    return
                view_ratings = resp["view_ratings_sum"]
                dev = abs(view_ratings - base_mass) / num_players
                max_mass_dev[0] = max(max_mass_dev[0], dev)
                if resp["watermark"] < last_watermark:
                    torn.append("watermark went backwards")
                    return
                last_watermark = resp["watermark"]

        reader_thread = threading.Thread(target=reader, daemon=True)
        offset = base_matches + stream_batch
        t0 = time.perf_counter()
        reader_thread.start()
        for i in range(stream_batches):
            start = offset + i * stream_batch
            srv.engine.ingest(
                winners[start : start + stream_batch],
                losers[start : start + stream_batch],
            )
        jax.block_until_ready(srv.engine.ratings)
        stream_s = time.perf_counter() - t0
        stop_event.set()
        reader_thread.join(timeout=60.0)
        elapsed = time.perf_counter() - t0
        sentinel.assert_no_new_compiles()
        # --- HARD gate 3: no query observed a torn view. The mass
        # deviation is in per-player rating points, gated by the same
        # tolerance as the rating diffs.
        if torn:
            raise EquivalenceError(float("inf"), tol)
        if not max_mass_dev[0] < tol:
            raise EquivalenceError(max_mass_dev[0], tol)
        qps = counts["queries"] / elapsed
        stats = srv.stats()
    finally:
        shutil.rmtree(snap_root, ignore_errors=True)

    streamed = stream_batch * stream_batches
    return {
        "metric": "arena_serve",
        "value": round(qps, 2),
        "unit": "queries_per_s",
        "vs_baseline": None,
        "params": {
            "base_matches": base_matches,
            "stream_batch": stream_batch,
            "stream_batches": stream_batches,
            "num_players": num_players,
            "batch_size": batch,
            "repeats": repeats,
            "seed": seed,
            "bootstrap_rounds": bootstrap_rounds,
            "max_staleness_matches": stream_batch,
            "host_cores": os.cpu_count() or 1,
        },
        "serve": {
            "snapshot_s": round(snapshot_s, 6),
            "restore_s": round(restore_s, 6),
            "snapshot_mb": round(manifest["bin_bytes"] / 1e6, 3),
            "snapshot_matches": manifest["num_matches"],
            "queries_under_ingest": counts["queries"],
            "ingest_stream_s": round(stream_s, 6),
            "stream_matches_per_s": round(streamed / stream_s),
            "view_refreshes": stats["view_refreshes"],
            "stale_serves": stats["stale_serves"],
            "max_view_mass_dev": round(max_mass_dev[0], 6),
            "steady_state_new_compiles": 0,  # sentinel raised otherwise
            "recompile_events_counted": stats["recompile_events"],
            "donation_skipped": stats["donation_skipped"],
        },
        "equivalence_ok": True,
        "max_rating_diff": round(max_diff, 6),
        "max_resume_diff": round(max_resume_diff, 6),
    }


def run_soak_benchmark():
    """The long mixed-workload soak (ROADMAP item 5's missing harness):
    concurrent overlapped ingest + a query thread + periodic durable
    snapshots + periodic bootstrap interval refreshes, all under the
    LIVE observability layer. One `arena_soak` JSON line reports the
    p50/p99 query latency, ingest throughput, and the queue-depth and
    staleness distributions — and TWO HARD GATES (rc 2) stand behind
    it: `recompile_events` counted by the production-mode sentinel
    must stay at ZERO across the whole measured window (update,
    bootstrap, packer thread — a recompile in the serving loop is a
    multi-second stall for every concurrent reader), and the final
    ratings must be equivalent to a sync replay of the same stream
    (plus the serve-mode torn-view invariants on every response).
    Since PR 13 a third gate rides along: the SLO burn-rate engine
    runs live over the sliding windows and must stay SILENT — a soak
    is the steady state, so any alert here is a broken alert."""
    base_matches = _env_int("ARENA_BENCH_MATCHES", 100_000)
    stream_batch = _env_int("ARENA_BENCH_DELTA", 10_000)
    soak_batches = _env_int("ARENA_BENCH_SOAK_BATCHES", 16)
    refresh_every = _env_int("ARENA_BENCH_SOAK_REFRESH_EVERY", 4)
    snapshot_every = _env_int("ARENA_BENCH_SOAK_SNAPSHOT_EVERY", 4)
    num_players = _env_int("ARENA_BENCH_PLAYERS", 1_000)
    batch = _env_int("ARENA_BENCH_BATCH", 8_192)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    capacity = _env_int("ARENA_BENCH_QUEUE_CAPACITY", 8)
    bootstrap_rounds = _env_int("ARENA_BENCH_BOOTSTRAP_ROUNDS", 8)
    tol = float(os.environ.get("ARENA_BENCH_TOL", EQUIVALENCE_TOL))

    total = base_matches + stream_batch * (1 + soak_batches)
    winners, losers = make_matches(total, num_players, seed)
    # Pin the bootstrap epoch padding to the soak's full horizon: every
    # interval refresh in the measured window then reuses ONE compiled
    # resampler no matter how far history has grown.
    min_epoch_batches = engine._pow2_ceil(-(-total // batch))

    obs_live = obs_pkg.Observability(trace_capacity=8192)
    _register_active_obs(obs_live)
    # Ops plane live for the whole soak (PR 13): 60x1s ring so the
    # full measured window stays inside the slow burn-rate window, and
    # the steady-state silence gate below reads real evaluations.
    obs_live.enable_ops(interval_s=1.0, intervals=60)
    # One-shot bench process (see run_ingest_benchmark): on a gate
    # failure the process exits and the daemon ops threads die with it.
    obs_live.start_ops()  # jaxlint: disable=missing-finally-for-paired-call
    srv = serving.ArenaServer(
        num_players=num_players,
        max_staleness_matches=stream_batch,
        bootstrap_rounds=bootstrap_rounds,
        obs=obs_live,
    )
    eng = srv.engine
    for start, stop in _batch_slices(base_matches, batch):
        eng.ingest(winners[start:stop], losers[start:stop])
    pipe = eng.start_pipeline(capacity=capacity)

    # Warmup — every legitimate compile happens HERE, outside the
    # gated window: the stream bucket, the horizon-padded bootstrap
    # epoch, the first serving view.
    w0 = winners[base_matches : base_matches + stream_batch]
    l0 = losers[base_matches : base_matches + stream_batch]
    eng.ingest_async(w0, l0)
    eng.flush()
    srv.refresh_intervals(batch_size=batch, min_epoch_batches=min_epoch_batches)
    query_ids = list(range(0, num_players, max(1, num_players // 8)))
    srv.query(leaderboard=(0, 10), players=query_ids, pairs=[(0, 1)])
    recompiles_after_warmup = srv.stats()["recompile_events"]

    h_depth = obs_live.histogram("arena_pipeline_queue_depth", base=1.0)
    lat_hist = obs_live.histogram("arena_query_latency_seconds")
    stale_hist = obs_live.histogram("arena_query_staleness_matches", base=1.0)
    base_mass = num_players * float(ratings.DEFAULT_BASE)
    stop_event = threading.Event()
    torn = []
    counts = {"queries": 0}
    max_mass_dev = [0.0]

    def reader():
        last_watermark = 0
        while not stop_event.is_set():
            resp = srv.query(
                leaderboard=(0, 10), players=query_ids, pairs=[(0, 1)]
            )
            counts["queries"] += 1
            page = [row["rating"] for row in resp["leaderboard"]]
            if page != sorted(page, reverse=True):
                torn.append("unsorted leaderboard page")
                return
            dev = abs(resp["view_ratings_sum"] - base_mass) / num_players
            max_mass_dev[0] = max(max_mass_dev[0], dev)
            if resp["watermark"] < last_watermark:
                torn.append("watermark went backwards")
                return
            last_watermark = resp["watermark"]

    snap_root = pathlib.Path(tempfile.mkdtemp(prefix="arena-soak-bench-"))
    snapshots_taken = 0
    refreshes_done = 0
    reader_thread = threading.Thread(target=reader, daemon=True)
    offset = base_matches + stream_batch
    try:
        t0 = time.perf_counter()
        reader_thread.start()
        for i in range(soak_batches):
            start = offset + i * stream_batch
            eng.ingest_async(
                winners[start : start + stream_batch],
                losers[start : start + stream_batch],
            )
            h_depth.record(pipe.pending())
            if (i + 1) % refresh_every == 0:
                srv.refresh_intervals(
                    batch_size=batch, min_epoch_batches=min_epoch_batches
                )
                refreshes_done += 1
            if (i + 1) % snapshot_every == 0:
                srv.snapshot(snap_root / "snap")
                snapshots_taken += 1
        eng.flush()
        jax.block_until_ready(eng.ratings)
        ingest_s = time.perf_counter() - t0
        stop_event.set()
        reader_thread.join(timeout=60.0)
        elapsed = time.perf_counter() - t0
        stats = srv.stats()
    finally:
        stop_event.set()
        shutil.rmtree(snap_root, ignore_errors=True)
    soak_recompiles = stats["recompile_events"] - recompiles_after_warmup

    # --- sync replay of the SAME stream (the equivalence anchor) -----
    eng_sync = engine.ArenaEngine(num_players)
    for start, stop in _batch_slices(base_matches, batch):
        eng_sync.ingest(winners[start:stop], losers[start:stop])
    eng_sync.ingest(w0, l0)
    for i in range(soak_batches):
        start = offset + i * stream_batch
        eng_sync.ingest(
            winners[start : start + stream_batch],
            losers[start : start + stream_batch],
        )
    max_diff = float(
        np.abs(np.asarray(eng.ratings) - np.asarray(eng_sync.ratings)).max()
    )

    # --- the soak HARD gates: equivalence, torn views, zero recompiles
    # (rc 2 on any breach — the mutation audit carries the gate-skipped
    # mutant; test_soak_bench_gate_is_hard is its named kill) ----------
    if not max_diff < tol:
        raise EquivalenceError(max_diff, tol)
    if torn or not max_mass_dev[0] < tol:
        raise EquivalenceError(float("inf"), tol)
    if soak_recompiles != 0:
        raise SoakGateError(
            f"{soak_recompiles} recompile event(s) counted during the "
            "soak's steady state; the compile-free contract (ROADMAP "
            "item 5) promises zero"
        )
    # --- SLO silence HARD gate (PR 13): a soak is the steady state by
    # definition — a burn-rate alert firing here means the alerting
    # math (or the system) is broken, rc 2 either way. ----------------
    slo_eval = obs_live.slo.evaluate()
    obs_live.stop_ops()
    if obs_live.slo.alerts_fired() != 0:
        fired = [
            name for name, o in slo_eval["objectives"].items()
            if o["fired_total"]
        ] or [f["slo"] for f in obs_live.slo.firings()]
        raise SoakGateError(
            f"SLO burn-rate alert(s) fired during the soak's steady "
            f"state: {fired}; a healthy steady state must stay silent"
        )

    streamed = stream_batch * soak_batches
    p50 = lat_hist.percentile(0.5)
    p99 = lat_hist.percentile(0.99)
    # Causal-diagnosis accounting for the line: orphan spans modulo the
    # explicit evicted-parent marker (tier-1 pins zero dangling), and
    # the exemplar behind the p99 query-latency bucket — the trace id a
    # human starts the postmortem from.
    dangling_orphans = sum(
        1 for _rec, reason in obs_live.tracer.orphans()
        if reason == "dangling"
    )
    p99_exemplar = lat_hist.exemplar(0.99)
    return {
        "metric": "arena_soak",
        "value": round(p99 * 1e3, 3) if p99 is not None else -1,
        "unit": "p99_query_latency_ms",
        "vs_baseline": None,
        "params": {
            "base_matches": base_matches,
            "stream_batch": stream_batch,
            "soak_batches": soak_batches,
            "refresh_every": refresh_every,
            "snapshot_every": snapshot_every,
            "num_players": num_players,
            "batch_size": batch,
            "seed": seed,
            "queue_capacity": capacity,
            "bootstrap_rounds": bootstrap_rounds,
            "max_staleness_matches": stream_batch,
            "host_cores": os.cpu_count() or 1,
        },
        "soak": {
            "elapsed_s": round(elapsed, 6),
            "queries": counts["queries"],
            "queries_per_s": round(counts["queries"] / elapsed, 2),
            "query_latency_ms": {
                "p50": round(p50 * 1e3, 3) if p50 is not None else None,
                "p99": round(p99 * 1e3, 3) if p99 is not None else None,
                "count": lat_hist.count,
            },
            "ingest_stream_s": round(ingest_s, 6),
            "stream_matches_per_s": round(streamed / ingest_s),
            "queue_depth": h_depth.snapshot(),
            "staleness_matches": stale_hist.snapshot(),
            "interval_refreshes": refreshes_done,
            "snapshots": snapshots_taken,
            "recompile_events": soak_recompiles,
            "donation_skipped": stats["donation_skipped"],
            "dropped_batches": stats["pipeline"]["dropped_batches"],
            "spilled_batches": stats["pipeline"]["spilled_batches"],
            "trace_spans_recorded": obs_live.tracer.recorded,
            "trace_dropped": obs_live.tracer.dropped,
            "trace_dangling_orphans": dangling_orphans,
            "p99_exemplar": p99_exemplar,
            "max_view_mass_dev": round(max_mass_dev[0], 6),
            "slo": {
                "alerts_fired": obs_live.slo.alerts_fired(),
                "objectives": sorted(slo_eval["objectives"]),
                "window_rotations": (
                    obs_live.windows.health()["rotations"]
                ),
                "profiler_samples": obs_live.profiler.samples,
            },
        },
        "equivalence_ok": True,
        "max_rating_diff": round(max_diff, 6),
    }


def run_frontend_benchmark():
    """The network-tier measurement: N producers + M readers over REAL
    localhost HTTP against `arena/net/`'s wire server and front door.

    Phase 1 (the steady state, sentinel-gated): producers POST fixed-
    size batches to /submit while readers page the query endpoints;
    the headline ``value`` is wire queries/s under that concurrent
    ingest. Phase 2 (forced overload): the merge worker is held and
    the shed knobs tightened, so continued submissions MUST shed —
    gating that the coalesce policy holds its staleness bound, ends
    every shed trace with the ``pipeline.dropped`` marker, and leaves
    zero dangling orphans at quiescence. The equivalence HARD gate
    then replays the front door's full applied log (both phases,
    summary updates included) through a sync single-producer engine in
    sequence order and requires bit-exact ratings.

    PR 16 (the fast wire path): readers mix singles with `POST /query`
    batches (each batched lookup counts as one wire query — same unit
    as a GET), every batch response must answer ALL its parts from ONE
    view generation, and a NEW cache-consistency HARD gate re-renders
    every current-generation cache entry from scratch and requires the
    cached bytes to match byte-for-byte."""
    base_matches = _env_int("ARENA_BENCH_MATCHES", 100_000)
    stream_batch = _env_int("ARENA_BENCH_DELTA", 10_000)
    num_players = _env_int("ARENA_BENCH_PLAYERS", 1_000)
    batch = _env_int("ARENA_BENCH_BATCH", 8_192)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    producers = _env_int("ARENA_BENCH_PRODUCERS", 4)
    readers = _env_int("ARENA_BENCH_READERS", 2)
    frontend_batches = _env_int("ARENA_BENCH_FRONTEND_BATCHES", 6)
    overload_batches = _env_int("ARENA_BENCH_OVERLOAD_BATCHES", 8)
    overload_capacity = _env_int("ARENA_BENCH_FRONTDOOR_CAPACITY", 4)
    shed_staleness = _env_int("ARENA_BENCH_SHED_STALENESS", 2 * stream_batch)
    queue_capacity = _env_int("ARENA_BENCH_QUEUE_CAPACITY", 8)
    tol = float(os.environ.get("ARENA_BENCH_TOL", EQUIVALENCE_TOL))

    total = base_matches + stream_batch * (
        1 + producers * (frontend_batches + overload_batches)
    )
    winners, losers = make_matches(total, num_players, seed)

    obs_live = obs_pkg.Observability(trace_capacity=16384)
    _register_active_obs(obs_live)
    # Configure the ops plane BEFORE the server: enable_ops() is
    # first-call-wins, so these knobs (1s sub-intervals, 60-deep ring)
    # hold when `ArenaServer.__init__` and `wire.start()` re-enter it.
    obs_live.enable_ops(interval_s=1.0, intervals=60)
    # Ownership transfer the analyzer cannot see: `wire.close()` at the
    # end of the run stops the ops plane (ArenaHTTPServer.close calls
    # obs.stop_ops()); on a gate failure the one-shot process exits and
    # the daemon ops threads die with it.
    obs_live.start_ops()  # jaxlint: disable=resource-leaked-on-exception
    srv = serving.ArenaServer(
        num_players=num_players,
        max_staleness_matches=stream_batch,
        obs=obs_live,
    )
    eng = srv.engine
    base_slices = _batch_slices(base_matches, batch)
    for start, stop in base_slices:
        eng.ingest(winners[start:stop], losers[start:stop])
    eng.start_pipeline(capacity=queue_capacity)
    # Phase 1 must not shed (a shed's coalesced summary is a NEW batch
    # shape, i.e. a legitimate compile — the steady-state window keeps
    # those out by giving the buffer room for the whole burst).
    frontdoor = net.FrontDoor(
        eng,
        capacity=producers * frontend_batches + 2,
        max_staleness_matches=total,
        record_applied=True,
    )
    wire = net.ArenaHTTPServer(srv, frontdoor=frontdoor).start()

    # Warmup over the wire: the stream bucket's compile + first view.
    warm = net.WireClient(wire.host, wire.port)
    w0 = winners[base_matches : base_matches + stream_batch]
    l0 = losers[base_matches : base_matches + stream_batch]
    status, _resp = warm.submit(w0, l0, producer="warmup")
    assert status == net.server.STATUS_ACCEPTED
    frontdoor.flush()
    warm.get("/leaderboard?offset=0&limit=10")
    warm.close()

    sentinel = sanitize.RecompileSentinel(update=eng.num_compiles)
    base_mass = num_players * float(ratings.DEFAULT_BASE)
    stop_event = threading.Event()
    torn = []
    counts = {"queries": 0, "requests": 0}
    counts_lock = threading.Lock()
    max_mass_dev = [0.0]

    def reader(rid):
        client = net.WireClient(wire.host, wire.port)
        last_watermark = 0
        pid = (rid * 7) % num_players
        mine = 0
        sent = 0
        # One dashboard-shaped batch: a page plus ten player rows and
        # ten h2h cells, each spec the payload of one single GET — 21
        # lookups amortized over ONE round-trip.
        batch_specs = [{"leaderboard": [0, 10]}]
        for k in range(10):
            batch_specs.append({"players": [(pid + k) % num_players]})
            batch_specs.append(
                {"pairs": [[(pid + k) % num_players,
                            (pid + k + 1) % num_players]]}
            )
        try:
            while not stop_event.is_set():
                for path in (
                    "/leaderboard?offset=0&limit=10",
                    f"/player/{pid}",
                    f"/h2h?a={pid}&b={(pid + 1) % num_players}",
                ):
                    status, resp = client.get(path)
                    if status != 200:
                        torn.append(f"reader {rid}: {path} -> {status}")
                        return
                    mine += 1
                    sent += 1
                    if resp["watermark"] < last_watermark:
                        torn.append(f"reader {rid}: watermark went backwards")
                        return
                    last_watermark = resp["watermark"]
                    if "leaderboard" in resp:
                        page = [row["rating"] for row in resp["leaderboard"]]
                        if page != sorted(page, reverse=True):
                            torn.append(f"reader {rid}: unsorted page")
                            return
                        dev = abs(resp["view_ratings_sum"] - base_mass) / num_players
                        max_mass_dev[0] = max(max_mass_dev[0], dev)
                # The batched read path (PR 16): 21 lookups, ONE HTTP
                # round-trip, ONE view — each part counts as one wire
                # query (the same unit as a single GET above).
                status, resp = client.batch_query(batch_specs)
                sent += 1
                if status != 200:
                    torn.append(f"reader {rid}: /query -> {status}")
                    return
                if resp["watermark"] < last_watermark:
                    torn.append(f"reader {rid}: watermark went backwards")
                    return
                last_watermark = resp["watermark"]
                seqs = {part["view_seq"] for part in resp["results"]}
                if seqs != {resp["view_seq"]}:
                    torn.append(
                        f"reader {rid}: batch split across views {seqs}"
                    )
                    return
                mine += len(resp["results"])
        finally:
            with counts_lock:
                counts["queries"] += mine
                counts["requests"] += sent
            client.close()

    def producer(pid, slices):
        client = net.WireClient(wire.host, wire.port)
        try:
            for start, stop in slices:
                status, resp = client.submit(
                    winners[start:stop], losers[start:stop],
                    producer=f"producer-{pid}",
                )
                if status != net.server.STATUS_ACCEPTED:
                    torn.append(f"producer {pid}: submit -> {status} {resp}")
                    return
        finally:
            client.close()

    # --- phase 1: the measured steady state --------------------------
    offset = base_matches + stream_batch
    producer_slices = []
    for p in range(producers):
        slices = []
        for i in range(frontend_batches):
            start = offset + (p * frontend_batches + i) * stream_batch
            slices.append((start, start + stream_batch))
        producer_slices.append(slices)
    offset += producers * frontend_batches * stream_batch

    reader_threads = [
        threading.Thread(target=reader, args=(r,), daemon=True)
        for r in range(readers)
    ]
    producer_threads = [
        threading.Thread(target=producer, args=(p, producer_slices[p]), daemon=True)
        for p in range(producers)
    ]
    t0 = time.perf_counter()
    for t in reader_threads:
        t.start()
    for t in producer_threads:
        t.start()
    for t in producer_threads:
        t.join(timeout=600.0)
    frontdoor.flush()
    ingest_s = time.perf_counter() - t0
    stop_event.set()
    for t in reader_threads:
        t.join(timeout=60.0)
    elapsed = time.perf_counter() - t0
    # Zero new compiles across every wire/producer/reader/merge thread
    # in the measured window (the steady-state contract over HTTP).
    sentinel.assert_no_new_compiles()
    if torn:
        raise EquivalenceError(float("inf"), tol)
    if not max_mass_dev[0] < tol:
        raise EquivalenceError(max_mass_dev[0], tol)
    # --- SLO HARD gate, half 1: SILENT at steady state ----------------
    # The burn-rate engine has been evaluating live over the sliding
    # windows since start_ops(); a healthy phase 1 (nothing shed,
    # nothing 5xx) must not have tripped a single alert.
    slo_engine = obs_live.slo
    slo_engine.evaluate()
    if slo_engine.alerts_fired() != 0:
        fired = sorted({f["slo"] for f in slo_engine.firings()})
        raise FrontendGateError(
            f"SLO burn-rate alert(s) fired during the steady state: "
            f"{fired}; an alert that fires on a healthy phase 1 is a "
            "broken alert (inverted threshold, wrong selector, or a "
            "window that never rotates)"
        )
    phase1_shed = frontdoor.shed_batches
    qps = counts["queries"] / elapsed
    streamed = producers * frontend_batches * stream_batch

    # --- phase 2: forced overload, the shedding-policy gates ----------
    frontdoor.reset_staleness_peak()
    frontdoor.set_policy(
        capacity=overload_capacity, max_staleness_matches=shed_staleness
    )
    frontdoor.pause()
    overload_slices = []
    for p in range(producers):
        slices = []
        for i in range(overload_batches):
            start = offset + (p * overload_batches + i) * stream_batch
            slices.append((start, start + stream_batch))
        overload_slices.append(slices)
    overload_threads = [
        threading.Thread(target=producer, args=(p, overload_slices[p]), daemon=True)
        for p in range(producers)
    ]
    for t in overload_threads:
        t.start()
    # Evaluate the burn-rate engine WHILE the overload runs: shedding
    # is happening right now, and the fast window must catch it live
    # (the alert has to fire during the incident, not in a post-mortem).
    while any(t.is_alive() for t in overload_threads):
        slo_engine.evaluate()
        time.sleep(0.02)
    for t in overload_threads:
        t.join(timeout=600.0)
    staleness_peak = frontdoor.max_staleness_seen
    staleness_bound = frontdoor.staleness_bound(stream_batch, producers=producers)
    slo_engine.evaluate()
    frontdoor.resume()
    frontdoor.flush()
    if torn:
        raise EquivalenceError(float("inf"), tol)
    shed_total = frontdoor.shed_batches
    overload_shed = shed_total - phase1_shed
    if overload_shed <= 0:
        raise FrontendGateError(
            "the forced-overload phase shed nothing: the shedding policy "
            "was never exercised, so its gates measured nothing"
        )
    if staleness_peak > staleness_bound:
        raise FrontendGateError(
            f"observed staleness {staleness_peak} matches exceeds the "
            f"configured bound {staleness_bound}; the coalesce policy's "
            "bounded-degradation contract broke"
        )
    dropped_markers = sum(
        1 for rec in obs_live.tracer.spans() if rec.name == "pipeline.dropped"
    )
    if dropped_markers < shed_total:
        raise FrontendGateError(
            f"{shed_total} batches were shed but only {dropped_markers} "
            "traces end with the pipeline.dropped marker; a shed request's "
            "trace must END, never dangle"
        )
    dangling = sum(
        1 for _rec, reason in obs_live.tracer.orphans() if reason == "dangling"
    )
    if dangling:
        raise FrontendGateError(
            f"{dangling} dangling orphan span(s) at quiescence; every wire "
            "request's trace must chain to an allocated root"
        )

    # --- SLO HARD gate, half 2: MUST fire under forced overload ------
    # Phase 2 dropped matches by design, so the submit-delivery burn
    # rate went through the roof — an engine that stayed silent would
    # never page on the real thing.
    slo_firings = slo_engine.firings("submit-delivery")
    if not slo_firings:
        raise FrontendGateError(
            "the forced-overload phase shed "
            f"{overload_shed} batches but the submit-delivery SLO "
            "burn-rate alert never fired; an alert that sleeps through "
            "a forced overload would sleep through a real one"
        )
    exemplar_tid = int(slo_firings[-1]["trace_id"])
    if exemplar_tid <= 0:
        raise FrontendGateError(
            "the submit-delivery burn-rate alert fired without an "
            "exemplar trace id; an alert must hand the operator one "
            "concrete offending request"
        )
    if not obs_live.tracer.trace(exemplar_tid):
        raise FrontendGateError(
            f"the burn-rate alert's exemplar trace {exemplar_tid} "
            "resolves to zero recorded spans; the exemplar must point "
            "at a real trace in the ring"
        )
    # --- /debug plane HARD gate: the ops plane over real HTTP --------
    # Every /debug endpoint must answer 200 with the standard envelope
    # (watermark + trace_id) — same wire contract as the query tier.
    debug_client = net.WireClient(wire.host, wire.port)
    debug_paths = (
        "/debug/window", "/debug/slo", "/debug/profile",
        f"/debug/trace/{exemplar_tid}",
    )
    try:
        for path in debug_paths:
            status, resp = debug_client.get(path)
            if status != 200:
                raise FrontendGateError(
                    f"GET {path} -> {status}; the ops plane must serve "
                    "live next to the query tier"
                )
            if not isinstance(resp, dict) or not (
                "watermark" in resp and "trace_id" in resp
            ):
                raise FrontendGateError(
                    f"GET {path} answered without the standard envelope "
                    "(watermark + trace_id); the /debug family wears the "
                    "same wire contract as every other endpoint"
                )
    finally:
        debug_client.close()

    # --- cache-consistency HARD gate (PR 16) --------------------------
    # The overload's final flush advanced the engine, so one fresh GET
    # first: it refreshes the view (staleness-bounded) and fills the
    # current cache generation (the prerender listener already re-
    # rendered the hot pages at refresh time). Then every entry of the
    # CURRENT generation is re-rendered from scratch and must match
    # the cached bytes byte-for-byte — cached bytes that differ from a
    # fresh render at the same watermark are a correctness bug, not a
    # perf detail.
    gate_client = net.WireClient(wire.host, wire.port)
    try:
        status, _resp = gate_client.get("/leaderboard?offset=0&limit=10")
        if status != 200:
            raise FrontendGateError(
                f"cache-gate populate GET -> {status}; cannot verify "
                "cache consistency without a live read"
            )
    finally:
        gate_client.close()
    cache_checked, cache_mismatches = wire.verify_cache_consistency()
    if cache_mismatches:
        raise FrontendGateError(
            f"{len(cache_mismatches)} cached response(s) differ from a "
            f"fresh render at the same watermark: {cache_mismatches[:4]}; "
            "the byte cache must be invisible to clients"
        )
    if cache_checked < 1:
        raise FrontendGateError(
            "the cache-consistency gate checked zero entries; the byte "
            "cache never held a current-generation response, so the "
            "fast path was never exercised"
        )

    # --- the equivalence HARD gate: sync replay of the applied log ---
    # (both phases, summary updates included) in sequence order.
    eng_sync = engine.ArenaEngine(num_players)
    for start, stop in base_slices:
        eng_sync.ingest(winners[start:stop], losers[start:stop])
    # The warmup batch rode the front door, so the applied log already
    # carries it — the log alone IS the post-base stream.
    for _kind, w, l in frontdoor.applied_log:
        eng_sync.ingest(w, l)
    max_diff = float(
        np.abs(np.asarray(eng.ratings) - np.asarray(eng_sync.ratings)).max()
    )
    if not max_diff < tol:
        raise EquivalenceError(max_diff, tol)

    stats = srv.stats()
    lat = obs_live.histogram(
        "arena_http_request_latency_seconds", endpoint="leaderboard"
    )
    p50 = lat.percentile(0.5)
    p99 = lat.percentile(0.99)
    # Per-endpoint wire latency from the WINDOWED view (satellite b):
    # rolling quantiles over the run's sliding window, per endpoint.
    window_delta = obs_live.windows.delta()
    wire_latency_by_endpoint = {}
    for ep in net.ENDPOINTS:
        wh = window_delta.histogram(
            "arena_http_request_latency_seconds", match={"endpoint": ep}
        )
        if wh is None or wh.count == 0:
            continue
        ep_p50, ep_p99 = wh.percentile(0.5), wh.percentile(0.99)
        wire_latency_by_endpoint[ep] = {
            "p50_ms": round(ep_p50 * 1e3, 3) if ep_p50 is not None else None,
            "p99_ms": round(ep_p99 * 1e3, 3) if ep_p99 is not None else None,
            "requests": int(wh.count),
        }
    window_rotations = obs_live.windows.health()["rotations"]
    profiler_samples = obs_live.profiler.samples
    slo_fired_total = slo_engine.alerts_fired()
    cache_stats = dict(stats["net"]["cache"])
    cache_reads = cache_stats["hits"] + cache_stats["misses"]
    front_end = wire.front_end
    wire.close()
    frontdoor.close()
    srv.close()
    return {
        "metric": "arena_frontend",
        "value": round(qps, 2),
        "unit": "wire_queries_per_s",
        "vs_baseline": None,
        "params": {
            "base_matches": base_matches,
            "stream_batch": stream_batch,
            "producers": producers,
            "readers": readers,
            "frontend_batches": frontend_batches,
            "overload_batches": overload_batches,
            "overload_capacity": overload_capacity,
            "shed_staleness_matches": shed_staleness,
            "num_players": num_players,
            "batch_size": batch,
            "seed": seed,
            "queue_capacity": queue_capacity,
            "host_cores": os.cpu_count() or 1,
        },
        "frontend": {
            "elapsed_s": round(elapsed, 6),
            "front_end": front_end,
            "wire_queries": counts["queries"],
            "wire_requests": counts["requests"],
            "wire_queries_per_s": round(qps, 2),
            "cache": {
                **cache_stats,
                "hit_rate": (
                    round(cache_stats["hits"] / cache_reads, 4)
                    if cache_reads else None
                ),
                "consistency_checked": cache_checked,
                "consistency_mismatches": 0,  # gate raised otherwise
            },
            "request_latency_ms": {
                "p50": round(p50 * 1e3, 3) if p50 is not None else None,
                "p99": round(p99 * 1e3, 3) if p99 is not None else None,
            },
            "ingest_stream_s": round(ingest_s, 6),
            "ingest_matches_per_s": round(streamed / ingest_s),
            "requests_by_endpoint": stats["net"]["requests_by_endpoint"],
            "requests_by_status": stats["net"]["requests_by_status"],
            "shed_batches": shed_total,
            "shed_matches_coalesced": frontdoor.shed_matches,
            "dropped_matches_staleness": frontdoor.dropped_matches,
            "shed_by_policy": stats["net"]["shed_batches_by_policy"],
            "summaries_applied": frontdoor.summaries_applied,
            "max_staleness_matches_seen": staleness_peak,
            "staleness_bound": staleness_bound,
            "dropped_marker_spans": dropped_markers,
            "trace_dangling_orphans": 0,  # gate raised otherwise
            "steady_state_new_compiles": 0,  # sentinel raised otherwise
            "max_view_mass_dev": round(max_mass_dev[0], 6),
            "wire_latency_by_endpoint": wire_latency_by_endpoint,
            "slo": {
                "alerts_fired": slo_fired_total,
                "exemplar_trace_id": exemplar_tid,
                "firings": [
                    {"slo": f["slo"], "burn_fast": round(f["burn_fast"], 3)}
                    for f in slo_firings
                ],
                "window_rotations": window_rotations,
                "profiler_samples": profiler_samples,
            },
            "debug_endpoints_ok": True,  # gate raised otherwise
        },
        "equivalence_ok": True,
        "max_rating_diff": round(max_diff, 6),
    }


def _dir_bytes(path):
    """Total on-disk payload of one snapshot directory."""
    return sum(
        f.stat().st_size for f in pathlib.Path(path).rglob("*") if f.is_file()
    )


def _replica_read_phase(targets, readers_per_target, duration_s,
                        num_players, errors):
    """Drive `readers_per_target` wire readers against every (host,
    port) target for `duration_s`; returns (total_queries, elapsed_s,
    per_target_queries). Readers alternate a leaderboard page with a
    player row — the dashboard-shaped single-GET mix."""
    stop = threading.Event()
    n_targets = len(targets)
    counts = [0] * (n_targets * readers_per_target)

    def reader(idx, host, port):
        client = net.WireClient(host, port)
        pid = (idx * 11) % num_players
        try:
            while not stop.is_set():
                for path in (
                    "/leaderboard?offset=0&limit=10", f"/player/{pid}"
                ):
                    status, _resp = client.get(path)
                    if status != 200:
                        errors.append(f"reader {idx}: {path} -> {status}")
                        return
                    counts[idx] += 1
        finally:
            client.close()

    threads = []
    for t_idx, (host, port) in enumerate(targets):
        for r in range(readers_per_target):
            idx = t_idx * readers_per_target + r
            threads.append(threading.Thread(
                target=reader, args=(idx, host, port), daemon=True
            ))
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    elapsed = time.perf_counter() - t0
    per_target = [
        sum(counts[t_idx * readers_per_target:(t_idx + 1) * readers_per_target])
        for t_idx in range(n_targets)
    ]
    return sum(counts), elapsed, per_target


def run_replica_benchmark():
    """The replicated-read-fleet measurement: incremental snapshots,
    applied-log shipping over real localhost HTTP, and replica reads
    under concurrent writer ingest. See the module docstring's replica
    paragraph for the five HARD gates."""
    from arena.net import replica as replica_mod

    base_matches = _env_int("ARENA_BENCH_MATCHES", 100_000)
    stream_batch = _env_int("ARENA_BENCH_DELTA", 10_000)
    num_players = _env_int("ARENA_BENCH_PLAYERS", 1_000)
    batch = _env_int("ARENA_BENCH_BATCH", 8_192)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    num_replicas = _env_int("ARENA_BENCH_REPLICAS", 2)
    producers = _env_int("ARENA_BENCH_PRODUCERS", 2)
    readers = _env_int("ARENA_BENCH_READERS", 2)
    catchup_batches = _env_int("ARENA_BENCH_CATCHUP_BATCHES", 4)
    catchup_timeout_s = float(
        os.environ.get("ARENA_BENCH_CATCHUP_TIMEOUT_S", 60.0)
    )
    window_s = float(os.environ.get("ARENA_BENCH_READ_WINDOW_S", 0.5))
    scaleout_min = float(
        os.environ.get("ARENA_BENCH_REPLICA_SCALEOUT_MIN", 0.75)
    )
    inc_ratio_min = float(os.environ.get("ARENA_BENCH_INC_RATIO_MIN", 5.0))
    tol = float(os.environ.get("ARENA_BENCH_TOL", 0.0))

    # 10% churn between the full cut and the incremental cut, in
    # front-door batches of the stream size (the log records the
    # replicas will replay are exactly these shapes).
    churn_batches = max(1, (base_matches // 10) // stream_batch)
    churn_matches = churn_batches * stream_batch
    streamed = producers * catchup_batches * stream_batch
    total = base_matches + churn_matches + stream_batch + streamed
    winners, losers = make_matches(total, num_players, seed)

    obs_live = obs_pkg.Observability(trace_capacity=16384)
    _register_active_obs(obs_live)
    obs_live.enable_ops(interval_s=1.0, intervals=60)
    # Same ownership transfer as the frontend mode: `wire.close()` in
    # the teardown stops the ops plane; on a gate failure the one-shot
    # process exits and the daemon ops threads die with it.
    obs_live.start_ops()  # jaxlint: disable=resource-leaked-on-exception
    srv = serving.ArenaServer(
        num_players=num_players,
        max_staleness_matches=stream_batch,
        obs=obs_live,
    )
    eng = srv.engine
    for start, stop in _batch_slices(base_matches, batch):
        eng.ingest(winners[start:stop], losers[start:stop])
    frontdoor = net.FrontDoor(
        eng,
        capacity=producers * catchup_batches + churn_batches + 4,
        max_staleness_matches=total,
        record_applied=True,
    )
    wire = net.ArenaHTTPServer(srv, frontdoor=frontdoor).start()

    snap_root = pathlib.Path(
        os.environ.get("ARENA_DEBUG_DIR")
        or tempfile.mkdtemp(prefix="arena-replica-bench-")
    )
    # --- the snapshot-size HARD gate: full, churn, incremental -------
    full_a = snap_root / "full-base"
    t0 = time.perf_counter()
    srv.snapshot(full_a)
    full_a_s = time.perf_counter() - t0

    cursor = base_matches
    for _ in range(churn_batches):
        frontdoor.submit(
            winners[cursor:cursor + stream_batch],
            losers[cursor:cursor + stream_batch],
            producer="churn",
        )
        cursor += stream_batch
    frontdoor.flush()

    inc_b = snap_root / "inc"
    t0 = time.perf_counter()
    srv.snapshot(inc_b, base=full_a)
    inc_s = time.perf_counter() - t0
    full_c = snap_root / "full-same-watermark"
    t0 = time.perf_counter()
    srv.snapshot(full_c)
    full_s = time.perf_counter() - t0
    inc_bytes = _dir_bytes(inc_b)
    full_bytes = _dir_bytes(full_c)
    bytes_ratio = full_bytes / inc_bytes if inc_bytes else float("inf")
    if bytes_ratio < inc_ratio_min:
        raise ReplicaGateError(
            f"incremental snapshot is only {bytes_ratio:.2f}x smaller "
            f"than a full cut at the same watermark ({inc_bytes} vs "
            f"{full_bytes} bytes at {churn_matches} churned matches); "
            f"the delta cut must stay >= {inc_ratio_min:g}x smaller or "
            "it is a full snapshot wearing a manifest chain"
        )
    inc_manifest = serving._read_manifest(inc_b)

    # --- the replica fleet: restore the incremental chain, tail /log --
    replicas = []
    try:
        for r_idx in range(num_replicas):
            r_obs = obs_pkg.Observability()
            r_srv = serving.ArenaServer(
                num_players=num_players,
                max_staleness_matches=stream_batch,
                obs=r_obs,
            )
            reader = replica_mod.ReplicaReader(
                r_srv, wire.host, wire.port, snapshot=inc_b
            )
            reader.start()
            r_wire = net.ArenaHTTPServer(r_srv, frontdoor=None).start()
            replicas.append((r_srv, reader, r_wire))

        # Warmup: one streamed batch compiles the replay bucket on
        # every replica engine (and the first view render on every
        # replica wire) BEFORE the sentinel arms.
        warm = net.WireClient(wire.host, wire.port)
        status, _resp = warm.submit(
            winners[cursor:cursor + stream_batch],
            losers[cursor:cursor + stream_batch],
            producer="warmup",
        )
        assert status == net.server.STATUS_ACCEPTED
        warm.close()
        cursor += stream_batch
        frontdoor.flush()
        warm_wm = int(eng.matches_applied)
        for _r_srv, reader, r_wire in replicas:
            reader.wait_for_watermark(warm_wm, timeout=catchup_timeout_s)
            probe = net.WireClient(r_wire.host, r_wire.port)
            probe.get("/leaderboard?offset=0&limit=10")
            probe.close()

        sentinel = sanitize.RecompileSentinel(**{
            "writer": eng.num_compiles,
            **{
                f"replica{i}": r_srv.engine.num_compiles
                for i, (r_srv, _reader, _r_wire) in enumerate(replicas)
            },
        })

        read_errors = []
        # --- phase A: one server, quiet (the scale-out denominator) --
        single_queries, single_elapsed, _per = _replica_read_phase(
            [(wire.host, wire.port)], readers, window_s, num_players,
            read_errors,
        )
        single_qps = single_queries / single_elapsed

        # --- phase B: concurrent wire ingest + replica reads; the
        # catch-up lag HARD gate ----------------------------------------
        staleness_peak = [0]
        ingest_stop = threading.Event()

        def staleness_monitor():
            while not ingest_stop.is_set():
                for _r_srv, reader, _r_wire in replicas:
                    lag = reader.staleness_matches()
                    if lag > staleness_peak[0]:
                        staleness_peak[0] = lag
                time.sleep(0.01)

        def producer(pid):
            client = net.WireClient(wire.host, wire.port)
            try:
                for b in range(catchup_batches):
                    start = (
                        base_matches + churn_matches + stream_batch
                        + (pid * catchup_batches + b) * stream_batch
                    )
                    status, _resp = client.submit(
                        winners[start:start + stream_batch],
                        losers[start:start + stream_batch],
                        producer=f"bench-{pid}",
                    )
                    if status != net.server.STATUS_ACCEPTED:
                        read_errors.append(f"producer {pid}: -> {status}")
                        return
            finally:
                client.close()

        monitor = threading.Thread(target=staleness_monitor, daemon=True)
        monitor.start()
        producer_threads = [
            threading.Thread(target=producer, args=(pid,), daemon=True)
            for pid in range(producers)
        ]
        ingest_t0 = time.perf_counter()
        for t in producer_threads:
            t.start()
        replica_targets = [
            (r_wire.host, r_wire.port) for _s, _r, r_wire in replicas
        ]
        during_queries, _during_elapsed, _per = _replica_read_phase(
            replica_targets, readers, window_s, num_players, read_errors,
        )
        for t in producer_threads:
            t.join(timeout=60.0)
        frontdoor.flush()
        ingest_s = time.perf_counter() - ingest_t0
        writer_wm = int(eng.matches_applied)
        catchup_t0 = time.perf_counter()
        try:
            for _r_srv, reader, _r_wire in replicas:
                reader.wait_for_watermark(warm_wm + streamed,
                                          timeout=catchup_timeout_s)
        except replica_mod.ReplicaError as exc:
            raise ReplicaGateError(
                f"catch-up lag blew its bound under concurrent wire "
                f"ingest: {exc}"
            ) from exc
        catchup_s = time.perf_counter() - catchup_t0
        ingest_stop.set()
        monitor.join(timeout=10.0)
        if writer_wm != warm_wm + streamed:
            raise ReplicaGateError(
                f"writer settled at watermark {writer_wm}, expected "
                f"{warm_wm + streamed}; the ingest phase lost matches"
            )

        # --- the bit-exactness HARD gate: equal watermark, zero diff --
        w_ratings, w_wm = srv.engine.ratings_snapshot()
        max_diff = 0.0
        for r_idx, (r_srv, _reader, _r_wire) in enumerate(replicas):
            r_ratings, r_wm = r_srv.engine.ratings_snapshot()
            if r_wm != w_wm:
                raise ReplicaGateError(
                    f"replica {r_idx} settled at watermark {r_wm}, "
                    f"writer at {w_wm}; no equal-watermark comparison "
                    "is possible"
                )
            diff = float(
                np.abs(np.asarray(w_ratings) - np.asarray(r_ratings)).max()
            )
            max_diff = max(max_diff, diff)
        if max_diff > tol:
            raise EquivalenceError(max_diff, tol)

        # --- phase C: the fleet, quiet (the scale-out numerator) ------
        aggregate_queries, aggregate_elapsed, per_replica = (
            _replica_read_phase(
                replica_targets, readers, window_s, num_players,
                read_errors,
            )
        )
        aggregate_qps = aggregate_queries / aggregate_elapsed
        if read_errors:
            raise ReplicaGateError(
                f"{len(read_errors)} wire worker(s) failed during the "
                f"measured phases: {read_errors[:4]}"
            )
        scaleout = aggregate_qps / single_qps if single_qps else 0.0
        if scaleout < scaleout_min:
            raise ReplicaGateError(
                f"aggregate read throughput across {num_replicas} "
                f"replicas is {aggregate_qps:.0f} q/s vs {single_qps:.0f} "
                f"q/s on one server ({scaleout:.2f}x < the "
                f"{scaleout_min:g}x floor); the replica read path is "
                "structurally slower than the server it mirrors"
            )

        # --- the zero-recompile HARD gate -----------------------------
        grew = sentinel.new_compiles()
        if grew:
            raise ReplicaGateError(
                f"steady-state record replay recompiled: {grew}; every "
                "shipped record is stream-batch shaped, so the bucket "
                "was compiled at warmup and must stay compiled"
            )

        records_shipped = sum(r.records_applied for _s, r, _w in replicas)
        segments = sum(r.segments_fetched for _s, r, _w in replicas)
        slo_names = [
            s.name for s in replicas[0][0].obs.slo.slos
        ]
        result = {
            "metric": "arena_replica",
            "value": round(aggregate_qps, 2),
            "unit": "replica_queries_per_s",
            "vs_baseline": None,
            "params": {
                "base_matches": base_matches,
                "stream_batch": stream_batch,
                "num_players": num_players,
                "batch_size": batch,
                "seed": seed,
                "replicas": num_replicas,
                "producers": producers,
                "readers_per_target": readers,
                "catchup_batches": catchup_batches,
                "read_window_s": window_s,
                "scaleout_min": scaleout_min,
                "inc_ratio_min": inc_ratio_min,
                "host_cores": os.cpu_count() or 1,
            },
            "replica": {
                "snapshot": {
                    "full_bytes": full_bytes,
                    "incremental_bytes": inc_bytes,
                    "bytes_ratio": round(bytes_ratio, 2),
                    "full_s": round(full_s, 6),
                    "full_base_s": round(full_a_s, 6),
                    "incremental_s": round(inc_s, 6),
                    "latency_ratio": round(full_s / inc_s, 2) if inc_s
                    else None,
                    "churn_matches": churn_matches,
                    "chain_depth": inc_manifest.get("chain_depth"),
                    "reuses_base_runs": inc_manifest.get("reuses_base_runs"),
                    "delta_matches": inc_manifest.get("delta_matches"),
                },
                "single_server_queries_per_s": round(single_qps, 2),
                "aggregate_queries_per_s": round(aggregate_qps, 2),
                "per_replica_queries": per_replica,
                "scaleout_ratio": round(scaleout, 3),
                "reads_during_ingest": during_queries,
                "catchup": {
                    "streamed_matches": streamed,
                    "streamed_batches": producers * catchup_batches,
                    "ingest_s": round(ingest_s, 6),
                    "catchup_s": round(catchup_s, 6),
                    "catchup_bound_s": catchup_timeout_s,
                    "staleness_peak_matches": int(staleness_peak[0]),
                    "records_shipped": records_shipped,
                    "segments_fetched": segments,
                },
                "staleness_slo_registered": "replica-staleness" in slo_names,
                "steady_state_new_compiles": 0,  # sentinel raised otherwise
            },
            "equivalence_ok": True,
            "max_rating_diff": round(max_diff, 6),
        }
    finally:
        for _r_srv, reader, r_wire in replicas:
            reader.close()
            r_wire.close()
            _r_srv.close()
        wire.close()
        frontdoor.close()
        srv.close()
        if not os.environ.get("ARENA_DEBUG_DIR"):
            shutil.rmtree(snap_root, ignore_errors=True)
    return result


def run_tenant_benchmark():
    """Multi-tenant fusion: N leaderboards through ONE jitted kernel.

    Phases: (1) within-bucket tenant GROWTH under a RecompileSentinel
    (HARD gate: zero new compiles while tenants are added inside one
    pow2 tenant bucket); (2) timed batched rounds — every tenant's
    matches in one fused update per round; (3) the dedicated loop —
    one `ArenaEngine` per tenant replays the same streams (compile
    warmup excluded from timing); (4) HARD gates: batched >= MIN_SPEEDUP
    x dedicated, every tenant's ratings row BIT-EXACT vs its dedicated
    engine (a zero-match tenant included), and the tenant-labeled
    counters on the single live registry reconciling exactly with the
    per-tenant match counts."""
    num_tenants = _env_int("ARENA_BENCH_TENANTS", 256)
    players = _env_int("ARENA_BENCH_TENANT_PLAYERS", 1_000)
    round_matches = _env_int("ARENA_BENCH_TENANT_ROUND", 256)
    rounds = _env_int("ARENA_BENCH_TENANT_ROUNDS", 4)
    seed = _env_int("ARENA_BENCH_SEED", 0)
    min_speedup = float(
        os.environ.get("ARENA_BENCH_TENANT_MIN_SPEEDUP", 5.0)
    )
    if num_tenants < 2:
        raise ValueError(f"tenant mode needs >= 2 tenants, got {num_tenants}")

    bucket = tenancy.tenant_bucket(num_tenants)
    # Start just past the bucket midpoint: every growth step below
    # stays INSIDE the final bucket, so the sentinel polices pure
    # bookkeeping (the gate's whole point).
    grow_from = max(2, min(num_tenants, bucket // 2 + 1))
    grow_steps = sorted(
        {
            grow_from + ((num_tenants - grow_from) * i) // 4
            for i in (1, 2, 3, 4)
        }
        | {num_tenants}
    )
    # Bit-exactness contract (arena/tenancy.py): both paths must pack
    # each round into the SAME row bucket. Every active tenant gets
    # exactly `round_matches` per round, and the dedicated engines pin
    # `min_bucket=row_bucket`, so both sides pad identically.
    row_bucket = engine.bucket_size(round_matches)
    # One tenant deliberately NEVER receives a match: its batched row
    # must stay base ratings bit-for-bit (the +-0.0 delta property).
    zero_tenant = num_tenants - 1

    obs = obs_pkg.Observability()
    _register_active_obs(obs)
    eng = tenancy.MultiTenantEngine(
        players, num_tenants=grow_from, min_bucket=row_bucket, obs=obs
    )

    # Per-tenant synthetic streams, sliced one round at a time; every
    # consumed slice is recorded for the dedicated replay.
    max_rounds = 2 + len(grow_steps) + rounds
    streams = {}
    for t in range(num_tenants):
        if t == zero_tenant:
            continue
        streams[t] = make_matches(
            max_rounds * round_matches, players, seed + 7919 * t
        )
    cursors = {t: 0 for t in streams}
    history = {t: [] for t in range(num_tenants)}

    def next_slice(t):
        start = cursors[t]
        cursors[t] = start + round_matches
        w = streams[t][0][start : start + round_matches]
        l = streams[t][1][start : start + round_matches]
        history[t].append((w, l))
        return w, l

    def batched_round(active):
        ws, ls = [], []
        for t in range(active):
            if t == zero_tenant:
                continue
            w, l = next_slice(t)
            ws.append(tenancy.compose_ids(w, t, players))
            ls.append(tenancy.compose_ids(l, t, players))
        eng.ingest(np.concatenate(ws), np.concatenate(ls))

    # --- phase 1: warmup, then within-bucket growth under the
    # sentinel (the zero-recompile HARD gate) -------------------------
    batched_round(grow_from)
    jax.block_until_ready(eng.ratings)
    sentinel = sanitize.RecompileSentinel(update=eng.num_compiles)
    for target in grow_steps:
        eng.ensure_tenants(target)
        batched_round(target)
    batched_round(num_tenants)  # warm-all: every tenant seen pre-timing
    jax.block_until_ready(eng.ratings)
    grew = sentinel.new_compiles()
    if grew:
        raise TenantGateError(
            f"tenant growth {grow_from} -> {num_tenants} inside one "
            f"tenant bucket ({bucket}) recompiled: {grew}; within-bucket "
            "growth is bookkeeping only — the tenant axis is pow2-padded "
            "exactly so new tenants never change a jitted shape"
        )

    # --- phase 2: the timed batched rounds ---------------------------
    t0 = time.perf_counter()
    for _ in range(rounds):
        batched_round(num_tenants)
    jax.block_until_ready(eng.ratings)
    batched_s = time.perf_counter() - t0
    grew = sentinel.new_compiles()
    if grew:
        raise TenantGateError(
            f"steady-state batched tenant rounds recompiled: {grew}; "
            "every round is (tenant_bucket, row_bucket)-shaped, so the "
            "fused update was compiled at warmup and must stay compiled"
        )

    # --- phase 3: the dedicated loop (one engine per tenant; replay
    # warmup excluded from timing) + the bit-exact HARD gate ----------
    batched_ratings = np.asarray(eng.ratings)
    dedicated_s = 0.0
    mismatched = []
    max_diff = 0.0
    for t in range(num_tenants):
        ded = engine.ArenaEngine(players, min_bucket=row_bucket, obs=None)
        hist = history[t]
        warm, timed = hist[: len(hist) - rounds], hist[len(hist) - rounds:]
        for w, l in warm:
            ded.ingest(w, l)
        jax.block_until_ready(ded.ratings)
        t0 = time.perf_counter()
        for w, l in timed:
            ded.ingest(w, l)
        jax.block_until_ready(ded.ratings)
        dedicated_s += time.perf_counter() - t0
        ded_ratings = np.asarray(ded.ratings)
        if not np.array_equal(batched_ratings[t], ded_ratings):
            mismatched.append(t)
            max_diff = max(
                max_diff,
                float(np.abs(batched_ratings[t] - ded_ratings).max()),
            )
    if mismatched:
        raise TenantGateError(
            f"{len(mismatched)} tenant(s) diverged bitwise from their "
            f"dedicated single-tenant engines (first: {mismatched[:4]}, "
            f"max diff {max_diff:.9f}); the fused row-parallel update "
            "promises BIT-EXACT per-tenant ratings, not a tolerance"
        )

    speedup = dedicated_s / batched_s if batched_s else float("inf")
    if speedup < min_speedup:
        raise TenantGateError(
            f"batched multi-tenant ingest is only {speedup:.2f}x the "
            f"{num_tenants}-engine dedicated loop (floor "
            f"{min_speedup:g}x); one fused (tenant, row) dispatch must "
            "beat per-tenant kernel launches or the tenancy layer has "
            "no reason to exist"
        )

    # --- phase 4: the ops-plane HARD gate — ONE registry, tenant-
    # labeled counters reconciling exactly ----------------------------
    per_tenant = obs.registry.counter_by_label(
        "arena_tenant_matches_total", "tenant"
    )
    expected = {
        str(t): round_matches * len(history[t])
        for t in range(num_tenants)
        if history[t]
    }
    if per_tenant != expected:
        missing = sorted(set(expected) - set(per_tenant), key=int)[:4]
        wrong = sorted(
            (k for k in per_tenant if per_tenant[k] != expected.get(k)),
            key=int,
        )[:4]
        raise TenantGateError(
            f"the tenant-labeled ops plane does not reconcile: "
            f"{len(per_tenant)} labeled series vs {len(expected)} active "
            f"tenants (missing e.g. {missing}, wrong e.g. {wrong}); one "
            "registry must account for every tenant's matches"
        )

    timed_matches = rounds * round_matches * (num_tenants - 1)
    return {
        "metric": "arena_tenant",
        "value": round(speedup, 2),
        "unit": "x_vs_dedicated_engines",
        "vs_baseline": None,
        "params": {
            "tenants": num_tenants,
            "players_per_tenant": players,
            "round_matches": round_matches,
            "rounds": rounds,
            "seed": seed,
            "grow_from": grow_from,
            "tenant_bucket": bucket,
            "row_bucket": row_bucket,
            "min_speedup": min_speedup,
            "host_cores": os.cpu_count() or 1,
        },
        "tenant": {
            "batched_s": round(batched_s, 6),
            "dedicated_s": round(dedicated_s, 6),
            "timed_matches": timed_matches,
            "batched_matches_per_s": round(timed_matches / batched_s)
            if batched_s else None,
            "growth_steps": grow_steps,
            "steady_state_new_compiles": 0,  # sentinel gate raised otherwise
            "bit_exact_tenants": num_tenants,
            "zero_match_tenant": zero_tenant,
            "ops_plane_tenants_labeled": len(per_tenant),
        },
        "equivalence_ok": True,
        "max_rating_diff": 0.0,  # np.array_equal per tenant, gated above
    }


def _spearman(x, y):
    """Spearman rank correlation between two score vectors (ranks by
    stable descending argsort — the leaderboard's own tie discipline)."""
    rx = np.empty(x.size)
    rx[np.argsort(-x, kind="stable")] = np.arange(x.size)
    ry = np.empty(y.size)
    ry[np.argsort(-y, kind="stable")] = np.arange(y.size)
    rx -= rx.mean()
    ry -= ry.mean()
    denom = float(np.sqrt((rx * rx).sum() * (ry * ry).sum()))
    return float((rx * ry).sum() / denom) if denom else 0.0


def _matchloop_ladder(players):
    """Tiered ground-truth skills: four hard tiers three logits apart,
    each with a narrow (±0.15) within-tier spread. This is the regime
    where match ALLOCATION matters: a cross-tier match is a ~95%+
    foregone conclusion that barely moves the ranking, so a policy
    that keeps spending budget there (random pairing does, ~75% of
    draws) converges slowly, while one that concentrates on
    still-overlapping intervals resolves the within-tier order with
    the same spend. A flat `np.linspace` ladder has no such structure
    — every neighbour gap is equally hard — and the two policies race
    inside Elo's K-factor noise floor there."""
    tiers = min(4, players)
    gap = 3.0
    strength = np.empty(players)
    bounds = np.linspace(0, players, tiers + 1).astype(int)
    top = gap * (tiers - 1) / 2.0
    for t in range(tiers):
        lo, hi = bounds[t], bounds[t + 1]
        if hi > lo:
            strength[lo:hi] = (top - gap * t) + np.linspace(
                0.15, -0.15, hi - lo
            )
    return strength


def _run_matchloop_arm(policy, players, n_per_request, budget,
                       corr_threshold, sustain, refresh_every,
                       bootstrap_rounds, seed, slo_threshold_s):
    """One closed-loop arm: a full server stack whose matches all come
    from its own matchmaker over real localhost HTTP. Ground truth is
    the tiered `_matchloop_ladder`; outcomes are Bernoulli draws from
    the Bradley-Terry win prob under an RNG seeded by (seed, policy) —
    so two arms with the same policy and seed replay bit-identically
    end to end. Convergence is a SUSTAINED crossing: `sustain`
    consecutive post-iteration correlation checks at or above the
    threshold, recorded as the submitted count at the streak's first
    check; the arm stops there (or at the budget cap). Returns the
    arm's convergence record plus the final ratings vector for the
    reproducibility gate."""
    from arena import match as match_mod

    strength = _matchloop_ladder(players)
    rng = np.random.default_rng([seed, match_mod.POLICIES.index(policy)])
    obs_live = obs_pkg.Observability(trace_capacity=8192)
    _register_active_obs(obs_live)
    obs_live.enable_ops(interval_s=1.0, intervals=60)
    # Ownership transfer the analyzer cannot see: wire.close() below
    # stops the ops plane; on a gate failure the one-shot process exits
    # and the daemon ops threads die with it.
    obs_live.start_ops()  # jaxlint: disable=resource-leaked-on-exception
    srv = serving.ArenaServer(
        num_players=players,
        max_staleness_matches=0,
        bootstrap_rounds=bootstrap_rounds,
        obs=obs_live,
    )
    eng = srv.engine
    frontdoor = net.FrontDoor(
        eng, capacity=64, max_staleness_matches=2 * budget
    )
    matchmaker = match_mod.Matchmaker(srv, slo_threshold_s=slo_threshold_s)
    wire = net.ArenaHTTPServer(
        srv, frontdoor=frontdoor, matchmaker=matchmaker
    ).start()
    client = net.WireClient(wire.host, wire.port)
    # Pin the bootstrap epoch pad to the arm's whole horizon so every
    # interval refresh over the growing history reuses ONE compiled
    # pad (the serve/soak modes' min_epoch_batches discipline).
    min_epoch = 1
    while min_epoch * 8192 < budget + 2 * n_per_request:
        min_epoch *= 2

    def play_round():
        status, resp = client.propose_matches(n_per_request, policy=policy)
        if status != 200:
            raise RuntimeError(f"/match answered {status}: {resp}")
        rows = resp["proposals"]
        a = np.asarray([r["a"] for r in rows], np.int64)
        b = np.asarray([r["b"] for r in rows], np.int64)
        p_a = 1.0 / (1.0 + np.exp(strength[b] - strength[a]))
        a_wins = rng.random(a.size) < p_a
        winners = np.where(a_wins, a, b).astype(np.int32)
        losers = np.where(a_wins, b, a).astype(np.int32)
        status, _resp = client.submit(
            winners, losers, producer=f"selfplay-{policy}"
        )
        if status != net.server.STATUS_ACCEPTED:
            raise RuntimeError(f"/submit answered {status}")
        frontdoor.flush()
        return int(a.size)

    try:
        # Warmup: one full loop turn compiles the update bucket and the
        # pair-scoring kernel; the first interval refresh compiles the
        # bootstrap pad. Only then does the sentinel arm.
        submitted = play_round()
        srv.refresh_intervals(
            num_rounds=bootstrap_rounds, seed=seed,
            min_epoch_batches=min_epoch,
        )
        sentinel = sanitize.RecompileSentinel(**{
            "update": eng.num_compiles,
            "bootstrap": eng.num_bootstrap_compiles,
            "matchmaker": matchmaker.num_compiles,
        })

        matches_to_corr = None
        streak = 0
        streak_start = None
        iterations = 0
        corr = 0.0
        t0 = time.perf_counter()
        while submitted < budget:
            submitted += play_round()
            iterations += 1
            if iterations % refresh_every == 0:
                srv.refresh_intervals(
                    num_rounds=bootstrap_rounds, seed=seed,
                    min_epoch_batches=min_epoch,
                )
            ratings_now, _wm = eng.ratings_snapshot()
            corr = _spearman(np.asarray(ratings_now, np.float64), strength)
            if corr >= corr_threshold:
                if streak == 0:
                    streak_start = submitted
                streak += 1
                if streak >= sustain:
                    # Converged: the streak's FIRST check is the count.
                    matches_to_corr = streak_start
                    break
            else:
                streak = 0
                streak_start = None
        elapsed = time.perf_counter() - t0
        final_ratings = np.asarray(eng.ratings_snapshot()[0]).copy()
        mm_stats = srv.stats()["net"]["matchmaker"]
        return {
            "policy": policy,
            "matches_to_corr": matches_to_corr,
            "final_corr": round(corr, 4),
            "submitted": submitted,
            "iterations": iterations,
            "elapsed_s": round(elapsed, 3),
            "proposal_requests": mm_stats["requests"],
            "proposals_served": mm_stats["proposals"],
            "slo_alerts_fired": obs_live.slo.alerts_fired(),
            "new_compiles": sentinel.new_compiles(),
            "ratings": final_ratings,
        }
    finally:
        client.close()
        wire.close()
        matchmaker.close()
        frontdoor.close()
        srv.close()


def run_matchloop_benchmark():
    """The matchmaking plane's acceptance harness: the deterministic
    closed-loop self-play soak (module docstring, ninth mode). Runs the
    active arm, the random control arm, and an active replay at equal
    match budget, then applies the four HARD gates — convergence
    advantage, seed-reproducibility, zero steady-state recompiles, and
    SLO silence."""
    players = _env_int("ARENA_BENCH_MATCHLOOP_PLAYERS", 64)
    n_per_request = _env_int("ARENA_BENCH_MATCHLOOP_PROPOSALS", 16)
    budget = _env_int("ARENA_BENCH_MATCHLOOP_BUDGET", 20_000)
    corr_threshold = float(os.environ.get("ARENA_BENCH_MATCHLOOP_CORR", 0.95))
    sustain = _env_int("ARENA_BENCH_MATCHLOOP_SUSTAIN", 6)
    refresh_every = _env_int("ARENA_BENCH_MATCHLOOP_REFRESH_EVERY", 8)
    bootstrap_rounds = _env_int("ARENA_BENCH_BOOTSTRAP_ROUNDS", 8)
    min_advantage = float(
        os.environ.get("ARENA_BENCH_MATCHLOOP_MIN_ADVANTAGE", 1.1)
    )
    slo_threshold_s = float(os.environ.get("ARENA_BENCH_MATCHLOOP_SLO_S", 0.25))
    seed = _env_int("ARENA_BENCH_SEED", 0)

    arm_args = (players, n_per_request, budget, corr_threshold, sustain,
                refresh_every, bootstrap_rounds, seed, slo_threshold_s)
    active = _run_matchloop_arm("active", *arm_args)
    random_arm = _run_matchloop_arm("random", *arm_args)
    replay = _run_matchloop_arm("active", *arm_args)

    # --- seed-reproducibility HARD gate ------------------------------
    ratings_equal = bool(
        np.array_equal(active["ratings"], replay["ratings"])
    )
    if not ratings_equal or active["matches_to_corr"] != replay["matches_to_corr"]:
        raise MatchloopGateError(
            "the closed loop is not seed-reproducible: two identical "
            f"active arms diverged (ratings bit-equal: {ratings_equal}, "
            f"matches-to-threshold {active['matches_to_corr']} vs "
            f"{replay['matches_to_corr']}) — the `# deterministic` "
            "apply/propose contracts promise bit-identical replays at "
            "a fixed seed"
        )

    # --- recompile + SLO-silence HARD gates, every arm ---------------
    for arm in (active, random_arm, replay):
        if arm["new_compiles"]:
            raise MatchloopGateError(
                f"steady-state recompiles in the {arm['policy']} arm: "
                f"{arm['new_compiles']} — every proposal/update/"
                "bootstrap shape must be warmed before the sentinel arms"
            )
        if arm["slo_alerts_fired"]:
            raise MatchloopGateError(
                f"{arm['slo_alerts_fired']} SLO alert(s) fired during "
                f"the {arm['policy']} arm — the soak requires the "
                "burn-rate engine silent throughout"
            )

    # --- the convergence HARD gate: active beats random --------------
    if active["matches_to_corr"] is None:
        raise MatchloopGateError(
            "active sampling never reached rank correlation "
            f"{corr_threshold:g} within the {budget}-match budget "
            f"(final {active['final_corr']}) — no convergence claim "
            "can be made"
        )
    random_reached = random_arm["matches_to_corr"]
    # A random arm that never converged still spent its whole budget:
    # score the advantage against that spend (a lower bound).
    random_effective = (
        random_reached if random_reached is not None
        else random_arm["submitted"]
    )
    advantage = random_effective / active["matches_to_corr"]
    if advantage < min_advantage:
        raise MatchloopGateError(
            f"uncertainty-driven sampling reached correlation "
            f"{corr_threshold:g} in {active['matches_to_corr']} matches "
            f"vs {random_effective} for random pairing ({advantage:.2f}x "
            f"< the {min_advantage:g}x floor) — active sampling must be "
            "measurably faster than random at equal budget"
        )

    def _arm_block(arm):
        return {
            k: v for k, v in arm.items()
            if k not in ("ratings", "new_compiles")
        }

    return {
        "metric": "arena_matchloop",
        "value": round(advantage, 3),
        "unit": "x_fewer_matches_vs_random",
        "vs_baseline": None,
        "params": {
            "players": players,
            "proposals_per_request": n_per_request,
            "budget_matches": budget,
            "corr_threshold": corr_threshold,
            "sustain_checks": sustain,
            "refresh_every": refresh_every,
            "bootstrap_rounds": bootstrap_rounds,
            "min_advantage": min_advantage,
            "slo_threshold_s": slo_threshold_s,
            "seed": seed,
            "host_cores": os.cpu_count() or 1,
        },
        "matchloop": {
            "active": _arm_block(active),
            "random": _arm_block(random_arm),
            "random_converged": random_reached is not None,
            "advantage": round(advantage, 3),
            "deterministic_replay_ok": True,  # bit-equal replay, gated
            "steady_state_new_compiles": 0,  # sentinel gate raised otherwise
            "slo_alerts_fired": 0,  # silence gate raised otherwise
        },
        "equivalence_ok": True,
        "max_rating_diff": 0.0,  # np.array_equal replay, gated above
    }


def main() -> int:
    rc = 0
    mode = os.environ.get("ARENA_BENCH_MODE", "elo")
    runners = {
        "ingest": (run_ingest_benchmark, "x_vs_cold_repack"),
        "pipeline": (run_pipeline_benchmark, "x_vs_sync_ingest"),
        "serve": (run_serve_benchmark, "queries_per_s"),
        "soak": (run_soak_benchmark, "p99_query_latency_ms"),
        "frontend": (run_frontend_benchmark, "wire_queries_per_s"),
        "replica": (run_replica_benchmark, "replica_queries_per_s"),
        "tenant": (run_tenant_benchmark, "x_vs_dedicated_engines"),
        "matchloop": (run_matchloop_benchmark, "x_fewer_matches_vs_random"),
    }
    runner, unit = runners.get(mode, (run_benchmark, "x_vs_naive_baseline"))
    try:
        line = json.dumps(runner())
    except EquivalenceError as exc:
        # A measured verdict, not a crash: the paths diverged, so the
        # line carries the divergence instead of a speedup — plus the
        # flight-recorder bundle path (the process's last flight) —
        # and the process exits the distinct equivalence-failure code.
        line = json.dumps(
            {
                "metric": "arena_bench_equivalence_failure",
                "value": -1,
                "unit": unit,
                "vs_baseline": None,
                "max_rating_diff": round(exc.max_diff, 6),
                "tolerance": exc.tol,
                "error": str(exc),
                "debug_bundle": _gate_debug_bundle(mode),
            }
        )
        rc = EXIT_EQUIVALENCE_FAILURE
    except ObsOverheadError as exc:
        # Same measured-verdict discipline: the instrumentation layer
        # measurably slowed the hot path, so the line carries the
        # regression instead of a speedup and the process exits rc 2.
        line = json.dumps(
            {
                "metric": "arena_bench_obs_overhead_failure",
                "value": -1,
                "unit": unit,
                "vs_baseline": None,
                "overhead_frac": round(exc.overhead, 4),
                "tolerance": exc.tol,
                "null_s": round(exc.null_s, 6),
                "live_s": round(exc.live_s, 6),
                "error": str(exc),
                "debug_bundle": _gate_debug_bundle(mode),
            }
        )
        rc = EXIT_EQUIVALENCE_FAILURE
    except SoakGateError as exc:
        # The soak's zero-recompile contract broke: a measured verdict
        # (the counter moved), never a crash.
        line = json.dumps(
            {
                "metric": "arena_bench_soak_gate_failure",
                "value": -1,
                "unit": unit,
                "vs_baseline": None,
                "error": str(exc),
                "debug_bundle": _gate_debug_bundle(mode),
            }
        )
        rc = EXIT_EQUIVALENCE_FAILURE
    except FrontendGateError as exc:
        # The wire tier's shedding contract broke (staleness bound,
        # dropped markers, orphans): a measured verdict, never a crash.
        line = json.dumps(
            {
                "metric": "arena_bench_frontend_gate_failure",
                "value": -1,
                "unit": unit,
                "vs_baseline": None,
                "error": str(exc),
                "debug_bundle": _gate_debug_bundle(mode),
            }
        )
        rc = EXIT_EQUIVALENCE_FAILURE
    except ReplicaGateError as exc:
        # The read fleet's replication contract broke (snapshot size,
        # catch-up bound, scale-out floor, recompile): a measured
        # verdict, never a crash.
        line = json.dumps(
            {
                "metric": "arena_bench_replica_gate_failure",
                "value": -1,
                "unit": unit,
                "vs_baseline": None,
                "error": str(exc),
                "debug_bundle": _gate_debug_bundle(mode),
            }
        )
        rc = EXIT_EQUIVALENCE_FAILURE
    except TenantGateError as exc:
        # A tenancy contract broke (speedup floor, per-tenant bit-
        # exactness, within-bucket recompile, ops-plane reconciliation):
        # a measured verdict, never a crash.
        line = json.dumps(
            {
                "metric": "arena_bench_tenant_gate_failure",
                "value": -1,
                "unit": unit,
                "vs_baseline": None,
                "error": str(exc),
                "debug_bundle": _gate_debug_bundle(mode),
            }
        )
        rc = EXIT_EQUIVALENCE_FAILURE
    except MatchloopGateError as exc:
        # The closed-loop soak's contract broke (convergence advantage,
        # seed-reproducibility, recompile, SLO silence): a measured
        # verdict, never a crash.
        line = json.dumps(
            {
                "metric": "arena_bench_matchloop_gate_failure",
                "value": -1,
                "unit": unit,
                "vs_baseline": None,
                "error": str(exc),
                "debug_bundle": _gate_debug_bundle(mode),
            }
        )
        rc = EXIT_EQUIVALENCE_FAILURE
    except Exception as exc:  # noqa: BLE001 — the one-line contract outranks
        line = json.dumps(
            {
                "metric": "arena_bench_internal_error",
                "value": -1,
                "unit": unit,
                "vs_baseline": None,
                "error": bench.exc_detail(exc),
            }
        )
    # Perf-watchdog history: with ARENA_BENCH_HISTORY set, every
    # emitted line (verdicts included — their distinct metric names are
    # simply never pinned) is ALSO appended to the JSON Lines history
    # file `python -m arena.obs.regress` compares against the pinned
    # BENCH_BASELINE.json. Best-effort: the stdout contract owns rc.
    history_path = os.environ.get("ARENA_BENCH_HISTORY")
    if history_path:
        try:
            with open(history_path, "a") as fh:
                fh.write(line + "\n")
        except OSError:
            pass
    # Same single-write discipline as bench.py: one fully-serialized
    # line, flush inside the guard, nothing appended after a failure.
    try:
        print(line)
        sys.stdout.flush()
        return rc
    except Exception:  # noqa: BLE001 — stdout itself is broken
        return 1


if __name__ == "__main__":
    sys.exit(main())
